"""Device-mesh construction for partitioned TPU slices.

A workload pod granted a ``walkai.io/tpu-<shape>`` slice sees exactly the
chips of that contiguous sub-mesh. This module maps the slice shape (and the
factored data/model/sequence parallel degrees) onto a `jax.sharding.Mesh`
whose axis layout follows ICI locality: the *model* (tensor-parallel) axis is
placed on the fastest-varying mesh dimension so tensor collectives ride
single-hop ICI links, and the *data* axis spans the remaining dimensions.

There is no reference analogue — the reference's demo workloads were
single-GPU torch pods; this is the TPU-first compute runtime that consumes
the slices the control plane creates.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from walkai_nos_tpu.tpu import topology

# Canonical mesh axis names, in the order they appear in every Mesh this
# module builds. Axes of size 1 are still present so PartitionSpecs are
# uniform across slice sizes.
AXIS_PIPE = "pipe"
AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_EXPERT = "expert"
AXIS_MODEL = "model"
AXIS_SEQ = "seq"

# Axis order = collective locality order, fastest-varying last: `seq`
# ring permutes ride nearest-neighbor links when sequence parallelism is
# on; with seq=1 (the common case) `model` is effectively fastest, so
# latency-critical TP psums stay on adjacent chips; `expert` all-to-alls
# sit one stride out; `pipe` varies slowest — stage handoffs are the
# rarest collective (one ppermute per microbatch tick). When combining
# seq>1 with model>1, TP groups are strided by the seq degree — prefer
# keeping one of the two at 1 on small slices.
ALL_AXES = (AXIS_PIPE, AXIS_DATA, AXIS_FSDP, AXIS_EXPERT, AXIS_MODEL, AXIS_SEQ)


@dataclass(frozen=True)
class MeshAxes:
    """Parallel degrees for one workload; product must equal device count."""

    data: int = 1
    fsdp: int = 1
    model: int = 1
    seq: int = 1
    expert: int = 1
    pipe: int = 1

    @property
    def total(self) -> int:
        return (
            self.data * self.fsdp * self.model * self.seq
            * self.expert * self.pipe
        )

    def as_shape(self) -> tuple[int, int, int, int, int, int]:
        return (
            self.pipe, self.data, self.fsdp,
            self.expert, self.model, self.seq,
        )


def _factor_axes(n: int, model: int | None, seq: int) -> MeshAxes:
    """Pick (data, model, seq) degrees for `n` devices (fsdp, expert and
    pipe stay 1 unless the caller passes explicit `MeshAxes`).

    Heuristic when `model` is unspecified: tensor parallelism up to 4-way
    (v5e host meshes are 2x4; a 4-chip TP group is one ICI row), the rest
    data parallel. Callers with strong opinions pass `model` explicitly.
    """
    if n % seq != 0:
        raise ValueError(f"seq degree {seq} does not divide device count {n}")
    rem = n // seq
    if model is None:
        model = math.gcd(rem, 4)
    if rem % model != 0:
        raise ValueError(f"model degree {model} does not divide {rem}")
    return MeshAxes(data=rem // model, fsdp=1, model=model, seq=seq)


def build_mesh(
    devices: Sequence[jax.Device] | None = None,
    *,
    axes: MeshAxes | None = None,
    model: int | None = None,
    seq: int = 1,
) -> Mesh:
    """Build a 6-axis ``Mesh`` (pipe, data, fsdp, expert, model, seq)
    over `devices`; axes not in play have size 1.

    Axis placement: devices are reshaped per ``ALL_AXES`` order — with
    seq=1, the *model* axis is the fastest-varying, so adjacent device
    ids (adjacent chips on the ICI mesh, per JAX's default TPU device
    order) form a tensor-parallel group and the latency-critical TP
    collectives stay on nearest-neighbor links; with seq>1 the ring
    permutes of sequence parallelism take those links instead.
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    if axes is None:
        axes = _factor_axes(len(devs), model, seq)
    if axes.total != len(devs):
        raise ValueError(
            f"mesh axes {axes.as_shape()} need {axes.total} devices, "
            f"got {len(devs)}"
        )
    arr = np.array(devs, dtype=object).reshape(axes.as_shape())
    return Mesh(arr, ALL_AXES)


def serving_mesh(
    tp_devices: int,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """The serving engine's tensor-parallel mesh: `tp_devices` chips on
    the `model` axis, every other axis size 1 (`models/serve.py`,
    `LMConfig.tp_devices`). Uses the FIRST `tp_devices` visible devices
    — adjacent device ids are adjacent chips on the ICI mesh (JAX's
    default TPU device order), so the per-layer TP psums ride
    nearest-neighbor links, exactly the `build_mesh` placement rule.
    On a CPU host with `--xla_force_host_platform_device_count=N`
    (the `WALKAI_TP_EMULATE` seam) the same mesh builds over virtual
    devices, which is how the tp parity suite pins tp=2/4 without a
    TPU."""
    devs = list(devices) if devices is not None else list(jax.devices())
    if tp_devices < 1:
        raise ValueError(f"tp_devices must be >= 1; got {tp_devices}")
    if len(devs) < tp_devices:
        raise ValueError(
            f"tp_devices={tp_devices} exceeds the {len(devs)} visible "
            f"devices (on CPU, set XLA_FLAGS="
            f"--xla_force_host_platform_device_count / the demo "
            f"server's WALKAI_TP_EMULATE knob before jax initializes)"
        )
    return build_mesh(devs[:tp_devices], axes=MeshAxes(model=tp_devices))


def slice_mesh(
    shape: str | topology.Shape,
    devices: Sequence[jax.Device] | None = None,
    *,
    model: int | None = None,
    seq: int = 1,
) -> Mesh:
    """Mesh for a workload granted one ``walkai.io/tpu-<shape>`` slice.

    `shape` is the slice's mesh shape (e.g. ``"2x2"``); the caller's visible
    devices must match its chip count. The slice's own geometry informs the
    default tensor-parallel degree: TP spans the slice's last (fastest) ICI
    dimension so a ``2x4`` slice defaults to 4-way TP × 2-way DP.
    """
    dims = topology.parse_shape(shape) if isinstance(shape, str) else shape
    chips = topology.shape_chip_count(dims)
    devs = list(devices) if devices is not None else list(jax.devices())
    if len(devs) != chips:
        raise ValueError(
            f"slice {topology.format_shape(dims)} has {chips} chips but "
            f"{len(devs)} devices are visible"
        )
    if model is None and seq == 1:
        model = dims[-1]
    return build_mesh(devs, model=model, seq=seq)

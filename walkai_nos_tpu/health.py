"""Health probes + metrics endpoint for the binaries.

The analogue of controller-runtime's health/metrics servers every reference
main wires (`healthz.Ping`, `cmd/gpupartitioner/gpupartitioner.go:106-113`;
metrics at `metrics.bindAddress`). Serves:

- /healthz  liveness (200 while the process runs)
- /readyz   readiness (200 once mark_ready(), 503 before/after)
- /metrics  Prometheus text exposition of registered gauges/counters

`Metrics` is the kube binaries' view of the ONE registry
implementation the repo has (`walkai_nos_tpu/obs/metrics.py` — the
serving engine and the install exporter expose the same surface): a
thin adapter keeping the imperative `counter_add`/`gauge_set` API the
controller runtime calls, over `obs.metrics.Registry` storage and
exposition.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from walkai_nos_tpu.obs.metrics import Registry


class Metrics(Registry):
    """The obs registry with the record-and-register-in-one-call API
    the kube binaries use (the instrument-object API is better for hot
    loops; reconcile-rate metrics don't need it)."""

    def counter_add(
        self, name: str, value: float = 1.0,
        labels: dict | None = None, help_text: str = "",
    ) -> None:
        self.counter(name, help_text).inc(value, labels)

    def gauge_set(
        self, name: str, value: float,
        labels: dict | None = None, help_text: str = "",
    ) -> None:
        self.gauge(name, help_text).set(value, labels)


class HealthServer:
    """Serves /healthz, /readyz, /metrics on one address."""

    def __init__(
        self,
        addr: str = ":8081",
        metrics: Metrics | None = None,
        serve_metrics: bool = True,
    ):
        host, _, port = addr.rpartition(":")
        self._host = host or "0.0.0.0"
        self._port = int(port)
        self.metrics = metrics or Metrics()
        # False when metrics live on a dedicated (proxied) address — the
        # probe port must not leak them unauthenticated.
        self._serve_metrics = serve_metrics
        self._ready = threading.Event()
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def mark_ready(self) -> None:
        self._ready.set()

    def mark_unready(self) -> None:
        self._ready.clear()

    @property
    def port(self) -> int:
        """Bound port (useful when constructed with port 0)."""
        assert self._server is not None
        return self._server.server_address[1]

    def start(self) -> None:
        ready = self._ready
        metrics = self.metrics
        serve_metrics = self._serve_metrics

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path == "/healthz":
                    self._respond(200, "ok")
                elif self.path == "/readyz":
                    if ready.is_set():
                        self._respond(200, "ok")
                    else:
                        self._respond(503, "not ready")
                elif self.path == "/metrics" and serve_metrics:
                    self._respond(200, metrics.render())
                else:
                    self._respond(404, "not found")

            def _respond(self, code: int, body: str) -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *args):  # quiet
                pass

        self._server = ThreadingHTTPServer((self._host, self._port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="health"
        )
        self._thread.start()

    def stop(self) -> None:
        if self._server:
            self._server.shutdown()
            self._server.server_close()
        if self._thread:
            self._thread.join(timeout=2.0)

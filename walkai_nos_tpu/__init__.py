"""walkai-nos, TPU-native.

A Kubernetes control plane that dynamically partitions TPU hosts into
right-sized sub-slices (contiguous sub-meshes of the ICI mesh) to match
pending-pod demand, plus the JAX/Pallas workload runtime that consumes those
slices.

Capability parity target: saguirregaray1/walkai-nos (see SURVEY.md).
"""

__version__ = "0.1.0"

"""Cluster-scope partitioner manager (`cmd/gpupartitioner/gpupartitioner.go:49-132`).

Loads the component config + known TPU geometries, optionally runs leader
election, and manages the NodeController (fresh-node init) + PodController
(pending pod -> repartition), with health probes on the manager address.
"""

from __future__ import annotations

import argparse
import logging
import sys

from walkai_nos_tpu.api import constants
from walkai_nos_tpu.cmd import _common
from walkai_nos_tpu.config import (
    PartitionerConfig,
    load_config,
    load_known_geometries_file,
)
from walkai_nos_tpu.controllers.partitioner.node_controller import NodeController
from walkai_nos_tpu.controllers.partitioner.pod_controller import (
    BatchingPodReconciler,
    PodController,
    make_node_event_mapper,
)
from walkai_nos_tpu.kube import predicates
from walkai_nos_tpu.kube.runtime import Controller, Manager

logger = logging.getLogger("tpupartitioner")


def build_manager(kube, config: PartitionerConfig) -> Manager:
    """Wire the two control loops (test seam: callers inject any KubeClient)."""
    manager = Manager()
    pod_controller = PodController(kube)
    if config.batch_window_timeout_s > 0:
        # Upstream pending-pod batching (`gpu_partitioner_config.yaml:23-33`):
        # a burst of pending pods is planned in one pass over one node
        # snapshot, with one spec write per node.
        batching = BatchingPodReconciler(
            pod_controller,
            timeout=config.batch_window_timeout_s,
            idle=config.batch_window_idle_s,
        )
        # Added before the pod watch so its worker is draining by the
        # time events flow; restarts with the manager on leader cycles.
        manager.add(batching)
        pod_reconcile = batching.reconcile
    else:
        pod_reconcile = pod_controller.reconcile
    pod_watch = Controller(
        constants.PARTITIONER_CONTROLLER_NAME,
        kube,
        "Pod",
        pod_reconcile,
        max_concurrent=1,  # `mig_controller.go:204`
    )
    manager.add(pod_watch)
    # Node events re-enqueue pending slice pods (the reference's watch
    # mapping, `mig_controller.go:180-207`) — no periodic pod polling.
    manager.add(
        Controller(
            "tpu-pending-pod-mapper",
            kube,
            "Node",
            make_node_event_mapper(kube, pod_watch.queue.add),
            predicates=[
                predicates.all_of(
                    predicates.has_label(constants.LABEL_TPU_PARTITIONING),
                    predicates.exclude_delete(),
                    # Status-only: the partitioner's own spec/plan writes
                    # must not re-enqueue the pods it just planned for.
                    predicates.status_annotations_changed(),
                )
            ],
        )
    )
    manager.add(
        Controller(
            "tpu-node-controller",
            kube,
            "Node",
            NodeController(kube).reconcile,
            predicates=[predicates.has_label(constants.LABEL_TPU_PARTITIONING)],
            max_concurrent=5,  # `node_controller.go:113`
        )
    )
    return manager


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="tpupartitioner")
    parser.add_argument("--config", help="TpuPartitionerConfig YAML path")
    parser.add_argument("--log-level", default="info")
    args = parser.parse_args(argv)
    _common.setup_logging(args.log_level)

    config = (
        load_config(args.config, "TpuPartitionerConfig")
        if args.config
        else PartitionerConfig()
    )
    if config.known_geometries_file:
        table = load_known_geometries_file(config.known_geometries_file)
        logger.info(
            "installed known TPU geometries for models: %s",
            ", ".join(sorted(table)),
        )

    kube = _common.build_kube_client()
    health = _common.start_health(
        config.manager.health_probe_addr, config.manager.metrics_addr
    )
    manager = build_manager(kube, config)
    stop = _common.wait_for_shutdown()

    if config.manager.leader_elect:
        from walkai_nos_tpu.kube.leader import LeaderElector

        elector = LeaderElector(
            kube,
            config.manager.leader_election_id or "tpupartitioner-leader",
            namespace=_common.current_namespace(),
            on_started_leading=manager.start,
            on_stopped_leading=manager.stop,
        )
        elector.start()
        health.mark_ready()
        stop.wait()
        elector.stop()
    else:
        manager.start()
        health.mark_ready()
        stop.wait()
        manager.stop()
    health.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Shared main-wiring: logging, signals, health server, kube client."""

from __future__ import annotations

import logging
import signal
import threading


def setup_logging(level: str = "info") -> None:
    logging.basicConfig(
        level=getattr(logging, level.upper(), logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )


def wait_for_shutdown() -> threading.Event:
    """Block-able event set on SIGTERM/SIGINT (manager ctx.Done analogue)."""
    stop = threading.Event()

    def _handler(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _handler)
    signal.signal(signal.SIGINT, _handler)
    return stop


def build_kube_client():
    """Real API-server client from in-cluster/KUBECONFIG credentials."""
    from walkai_nos_tpu.kube.rest import RestKubeClient

    return RestKubeClient()


def start_health(addr: str):
    from walkai_nos_tpu.health import HealthServer
    from walkai_nos_tpu.kube import runtime

    server = HealthServer(addr)
    server.start()
    # Controller reconcile metrics flow to this binary's /metrics endpoint
    # (the controller-runtime built-in registry analogue).
    runtime.set_metrics_registry(server.metrics)
    return server

"""Shared main-wiring: logging, signals, health server, kube client."""

from __future__ import annotations

import logging
import os
import signal
import threading

_SA_NAMESPACE_FILE = (
    "/var/run/secrets/kubernetes.io/serviceaccount/namespace"
)


def current_namespace(default: str = "default") -> str:
    """The namespace this process runs in: POD_NAMESPACE env (downward
    API) first, then the service-account namespace file. Leader-election
    leases must live here — RBAC only grants Lease access in the release
    namespace."""
    ns = os.environ.get("POD_NAMESPACE")
    if ns:
        return ns
    try:
        with open(_SA_NAMESPACE_FILE) as f:
            return f.read().strip() or default
    except OSError:
        return default


def setup_logging(level: str = "info") -> None:
    logging.basicConfig(
        level=getattr(logging, level.upper(), logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )


def wait_for_shutdown() -> threading.Event:
    """Block-able event set on SIGTERM/SIGINT (manager ctx.Done analogue)."""
    stop = threading.Event()

    def _handler(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _handler)
    signal.signal(signal.SIGINT, _handler)
    return stop


def build_kube_client():
    """Real API-server client from in-cluster/KUBECONFIG credentials."""
    from walkai_nos_tpu.kube.rest import RestKubeClient

    return RestKubeClient()


class _Servers:
    """Health (+ optional separate metrics) servers as one handle."""

    def __init__(self, health, metrics_server):
        self._health = health
        self._metrics_server = metrics_server
        self.metrics = health.metrics

    def mark_ready(self) -> None:
        self._health.mark_ready()

    def mark_unready(self) -> None:
        self._health.mark_unready()

    def stop(self) -> None:
        self._health.stop()
        if self._metrics_server:
            self._metrics_server.stop()


def start_health(addr: str, metrics_addr: str | None = None):
    """Start the probe server; with `metrics_addr`, serve /metrics on its
    own address instead (so it can bind 127.0.0.1 behind a kube-rbac-proxy
    while probes stay reachable by the kubelet)."""
    from walkai_nos_tpu.health import HealthServer
    from walkai_nos_tpu.kube import runtime

    separate = bool(metrics_addr) and metrics_addr != addr
    health = HealthServer(addr, serve_metrics=not separate)
    health.start()
    metrics_server = None
    if separate:
        metrics_server = HealthServer(metrics_addr, metrics=health.metrics)
        metrics_server.start()
    # Controller reconcile metrics flow to this binary's /metrics endpoint
    # (the controller-runtime built-in registry analogue).
    runtime.set_metrics_registry(health.metrics)
    return _Servers(health, metrics_server)

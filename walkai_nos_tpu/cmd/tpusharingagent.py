"""Per-node sharing agent (`cmd/gpuagent/gpuagent.go:54-152` analogue).

DaemonSet for chip-count-sharing nodes (the MPS/slicing analogue). The
reference fork reduced sharing to report-only; this agent restores the
actuation half the way the quota scheduler restored ERQ: a ShareActuator
turns spec annotations into advertised share devices
(`deviceplugin/share_manager.py`), and the Reporter closes the loop with
status annotations + plan acks. Refuses to run if the host has tiled
slices materialized, mirroring gpuagent's refusal on MIG-enabled GPUs
(`AnyMigEnabledGpu`, :109-117, :146).
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

from walkai_nos_tpu.api import constants
from walkai_nos_tpu.cmd import _common
from walkai_nos_tpu.cmd.tpuagent import build_tpudev
from walkai_nos_tpu.config import AgentConfig, load_config
from walkai_nos_tpu.controllers.tpuagent.reporter import Reporter
from walkai_nos_tpu.controllers.tpuagent.shared import SharedState
from walkai_nos_tpu.kube import predicates
from walkai_nos_tpu.kube.runtime import Controller, Manager
from walkai_nos_tpu.tpu.errors import TpuError
from walkai_nos_tpu.tpu.sharing.client import SharingClient
from walkai_nos_tpu.tpu.sharing.profile import extract_shared_profile_name

logger = logging.getLogger("tpusharingagent")


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="tpusharingagent")
    parser.add_argument("--config", help="TpuAgentConfig YAML path")
    parser.add_argument("--log-level", default="info")
    parser.add_argument(
        "--pod-resources-socket", default=constants.POD_RESOURCES_SOCKET
    )
    args = parser.parse_args(argv)
    _common.setup_logging(args.log_level)

    node_name = os.environ.get(constants.ENV_NODE_NAME)
    if not node_name:
        logger.error("%s env var is required", constants.ENV_NODE_NAME)
        return 1

    config = (
        load_config(args.config, "TpuAgentConfig") if args.config else AgentConfig()
    )

    tpudev = build_tpudev()
    try:
        tiled = tpudev.list_slices()
    except TpuError as e:
        logger.error("device layer unavailable: %s", e)
        return 1
    if tiled:
        # Tiled hosts belong to the tpuagent (`gpuagent.go:109-117`).
        logger.error(
            "host has %d tiled slice(s); sharing agent cannot run here",
            len(tiled),
        )
        return 1

    from walkai_nos_tpu.resource.lister import PodResourcesClient

    sharing_client = SharingClient(PodResourcesClient(args.pod_resources_socket))
    kube = _common.build_kube_client()
    health = _common.start_health(
        config.manager.health_probe_addr, config.manager.metrics_addr
    )

    try:
        host = tpudev.get_topology()
    except TpuError as e:
        logger.error("device layer unavailable: %s", e)
        return 1
    from walkai_nos_tpu.controllers.tpuagent.share_actuator import (
        ShareActuator,
    )
    from walkai_nos_tpu.deviceplugin.share_manager import SharePluginManager

    share_manager = SharePluginManager(len(host.chips))
    share_manager.start()

    from walkai_nos_tpu.kube.sharedwatch import SharedWatchClient

    # Reporter and ShareActuator both watch this Node: one upstream
    # stream (informer semantics), owned by the manager.
    kube = SharedWatchClient(kube)
    shared = SharedState()
    manager = Manager()
    manager.own(kube)
    manager.add(
        Controller(
            "tpusharing-reporter",
            kube,
            "Node",
            Reporter(
                kube,
                sharing_client,
                shared,
                node_name,
                refresh_interval=config.report_interval_s,
                profile_extractor=extract_shared_profile_name,
            ).reconcile,
            predicates=[
                predicates.matching_name(node_name),
                predicates.exclude_delete(),
            ],
        )
    )
    manager.add(
        Controller(
            "tpusharing-actuator",
            kube,
            "Node",
            ShareActuator(
                kube,
                shared,
                node_name,
                share_manager,
                sharing_client=sharing_client,
            ).reconcile,
            predicates=[
                predicates.matching_name(node_name),
                predicates.exclude_delete(),
                predicates.annotations_changed(),
            ],
        )
    )
    stop = _common.wait_for_shutdown()
    manager.start()
    health.mark_ready()
    stop.wait()
    manager.stop()
    share_manager.stop()
    health.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""replay: re-execute a capture log offline and verify every digest.

The one-command incident workflow over the capture plane
(`obs/capture.py` -> `sim/replay.py`): load a capture recorded by a
`ContinuousBatcher(capture=...)` (the demo server's
WALKAI_CAPTURE_DIR, or a `/debug/capture/download` body saved to
disk), rebuild the engine from the capture's config fingerprint,
re-submit the recorded traffic, and verify every completion token
stream. Exit 0 means token-identical replay (zero divergent
requests); any divergence exits 1 AFTER running first-divergence
triage — the earliest divergent request is re-run solo to classify
batch-dependent vs config-dependent, the first divergent (request,
token) is reported, and a flight-recorder bundle is dumped.

Usage:

    python -m walkai_nos_tpu.cmd.replay CAPTURE [options]

    CAPTURE                 capture-*.jsonl file, or the directory
                            holding a rotated set
    --run N                 which engine run to replay when the
                            directory spans server restarts (request
                            ids restart per run; default the latest)
    --override KEY=VALUE    replay under a changed knob (repeatable):
                            engine knobs (loop_steps=1, spec=true,
                            prefix_cache=false, slots=8, ...) or
                            LMConfig fields (kv_dtype=int8-sim,
                            tp_devices=2, ...)
    --timing asap|original  as-fast-as-possible (default) or re-paced
                            to the recorded arrival offsets
    --speed X               original-timing speedup factor
    --init-seed N           rebuild the weight tree from
                            DecoderLM(cfg).init_params(PRNGKey(N))
                            (default 0 — the demo server's init); a
                            digest mismatch vs the capture's
                            fingerprint is warned about up front
    --draft-init-seed N     spec-replay draft init (any draft weights
                            replay token-identically; this only
                            matters for reproducing acceptance rates)
    --flight-dir DIR        where the divergence bundle lands
    --json                  machine-readable summary on stdout

Weights come from an init seed because captures store a DIGEST, not
the tree: the recorded `weights_crc32` is checked against the rebuilt
tree so "you replayed under different weights" is said out loud
before the divergence report blames a config axis.
"""

from __future__ import annotations

import argparse
import json
import sys

__all__ = ["main", "parse_args", "parse_override"]


def parse_override(text: str) -> tuple[str, object]:
    """KEY=VALUE -> (key, coerced value): bools ('true'/'false'),
    ints, floats, then the raw string (dtype names like 'int8-sim'
    stay strings)."""
    if "=" not in text:
        raise argparse.ArgumentTypeError(
            f"override must be KEY=VALUE; got {text!r}"
        )
    key, raw = text.split("=", 1)
    low = raw.strip().lower()
    if low in ("true", "false"):
        return key.strip(), low == "true"
    for cast in (int, float):
        try:
            return key.strip(), cast(raw)
        except ValueError:
            pass
    return key.strip(), raw


def parse_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        description=(
            "re-execute a serving capture offline and verify every "
            "completion digest (sim/replay.py)"
        )
    )
    parser.add_argument(
        "capture",
        help="capture-*.jsonl file or the directory holding one",
    )
    parser.add_argument(
        "--override", action="append", default=[],
        type=parse_override, metavar="KEY=VALUE",
        help="replay under a changed engine knob or LMConfig field "
             "(repeatable)",
    )
    parser.add_argument(
        "--run", type=int, default=None,
        help="which engine run to replay when the capture dir spans "
             "server restarts (0-based, negative from the end; "
             "default: the latest run)",
    )
    parser.add_argument(
        "--timing", choices=("asap", "original"), default="asap",
    )
    parser.add_argument("--speed", type=float, default=1.0)
    parser.add_argument("--init-seed", type=int, default=0)
    parser.add_argument("--draft-init-seed", type=int, default=0)
    parser.add_argument("--flight-dir", default=None)
    parser.add_argument("--json", action="store_true")
    return parser.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    from walkai_nos_tpu.sim.replay import (
        build_config,
        load_capture,
        replay_capture,
        triage_divergence,
    )

    capture = load_capture(args.capture, run=args.run)
    overrides = dict(args.override)
    cfg, _ = build_config(capture.fingerprint, overrides)

    import jax

    from walkai_nos_tpu.models.lm import DecoderLM
    from walkai_nos_tpu.obs.capture import tree_crc32

    params = DecoderLM(cfg).init_params(
        jax.random.PRNGKey(args.init_seed)
    )
    notes = []
    if capture.runs > 1:
        notes.append(
            f"capture spans {capture.runs} engine runs (request ids "
            f"restart per run); replaying run {capture.run} — select "
            f"another with --run"
        )
    recorded_crc = capture.fingerprint.get("weights_crc32")
    # The engine quantizes/expands its own copy at build, so compare
    # the RAW tree only when the capture served raw weights too;
    # either way the replay engine's own fingerprint (in the triage
    # bundle) carries the authoritative post-build digest. ENGINE-
    # knob overrides (loop_steps, prefix_cache, ...) cannot touch
    # the tree, so they must not suppress the check — only an
    # LMConfig-field override invalidates the raw comparison.
    from walkai_nos_tpu.sim.replay import ENGINE_KNOBS

    cfg_overridden = any(k not in ENGINE_KNOBS for k in overrides)
    if (
        recorded_crc is not None
        and not cfg_overridden
        and cfg.w_dtype == "model"
        and cfg.tp_devices == 1
        and tree_crc32(params) != recorded_crc
    ):
        notes.append(
            f"weights digest mismatch: rebuilt tree (init seed "
            f"{args.init_seed}) != capture's weights_crc32 "
            f"{recorded_crc} — divergence, if any, is "
            f"config_dependent by construction"
        )
    report = replay_capture(
        capture, params,
        overrides=overrides,
        timing=args.timing,
        speed=args.speed,
        draft_seed=args.draft_init_seed,
    )
    verdict = None
    if not report.ok:
        verdict = triage_divergence(
            capture, report, params,
            overrides=overrides,
            draft_seed=args.draft_init_seed,
            flight_dir=args.flight_dir,
        )
    summary = {
        **report.summary(),
        "capture_files": capture.files,
        "notes": notes,
        "triage": verdict,
    }
    if args.json:
        print(json.dumps(summary, default=str))
    else:
        for note in notes:
            print(f"note: {note}")
        print(
            f"replayed {summary['requests']} request(s) "
            f"({summary['verified']} verified) from fingerprint "
            f"{summary['fingerprint']}: "
            + ("token-identical" if report.ok else
               f"{summary['divergent']} DIVERGENT")
        )
        if verdict is not None:
            print(
                f"first divergence: request {verdict['rid']} token "
                f"{verdict['token_index']} "
                f"(expected {verdict['expected_token']}, got "
                f"{verdict['got_token']}) — "
                f"{verdict['classification']}; bundle: "
                f"{verdict['bundle_path']}"
            )
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())

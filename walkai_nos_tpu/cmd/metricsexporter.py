"""One-shot install-telemetry hook (`cmd/metricsexporter/metricsexporter.go:33-91`).

Helm post-install hook: read the metrics YAML the chart rendered (install
UUID, node inventory, chart values, enabled components — schema per
`cmd/metricsexporter/metrics/metrics.go:24-42`), POST it as JSON to the
telemetry endpoint. EVERY error path exits 0 — telemetry must never fail an
install (the reference swallows all errors the same way).
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import urllib.request

import yaml

from walkai_nos_tpu.cmd import _common

logger = logging.getLogger("metricsexporter")


def build_metrics(raw: dict, kube=None) -> dict:
    """Metrics schema (`metrics.go:24-42` analogue). If a kube client is
    given, enrich with live node inventory like the reference does."""
    metrics = {
        "installation_uuid": raw.get("installationUUID", ""),
        "chart_values": raw.get("chartValues", {}),
        "components": raw.get("components", {}),
        "nodes": raw.get("nodes", []),
    }
    if kube is not None:
        try:
            nodes = []
            for node in kube.list("Node"):
                meta = node.get("metadata") or {}
                status = node.get("status") or {}
                nodes.append(
                    {
                        "name": meta.get("name", ""),
                        "labels": meta.get("labels") or {},
                        "capacity": status.get("capacity") or {},
                    }
                )
            metrics["nodes"] = nodes
        except Exception as e:
            # The hook pod may run with a low-privilege SA (RBAC denies
            # node lists); the chart-rendered inventory in `raw` stands.
            logger.warning("node inventory unavailable: %s", e)
    return metrics


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="metricsexporter")
    parser.add_argument("--metrics-file", required=True)
    parser.add_argument(
        "--endpoint", default="https://telemetry.walkai.io/v1/nos-metrics"
    )
    parser.add_argument("--log-level", default="info")
    args = parser.parse_args(argv)
    _common.setup_logging(args.log_level)

    # Exit 0 on every failure (`metricsexporter.go:33-91`).
    try:
        with open(args.metrics_file) as f:
            raw = yaml.safe_load(f) or {}
    except Exception as e:
        logger.warning("cannot read metrics file: %s", e)
        return 0
    kube = None
    try:
        kube = _common.build_kube_client()
    except Exception:
        pass
    try:
        metrics = build_metrics(raw, kube)
        req = urllib.request.Request(
            args.endpoint,
            data=json.dumps(metrics).encode(),
            method="POST",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10.0) as resp:
            resp.read()
        logger.info("install metrics sent")
    except Exception as e:
        logger.warning("cannot send metrics: %s", e)
    return 0


if __name__ == "__main__":
    sys.exit(main())

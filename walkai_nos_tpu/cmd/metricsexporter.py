"""One-shot install-telemetry hook (`cmd/metricsexporter/metricsexporter.go:33-91`).

Helm post-install hook: read the metrics YAML the chart rendered (install
UUID, node inventory, chart values, enabled components — schema per
`cmd/metricsexporter/metrics/metrics.go:24-42`), POST it as JSON to the
telemetry endpoint. EVERY error path exits 0 — telemetry must never fail an
install (the reference swallows all errors the same way).

The same payload is also exposed through the repo's unified metrics
registry (`walkai_nos_tpu/obs/metrics.py` — the registry the serving
engine's /metrics and the kube binaries' health servers serve):
`registry_from_metrics` turns the install inventory into the
`nos_install_*` gauges declared in `obs/catalog.py`, and `--prom-file`
writes the Prometheus text exposition to a file (the node-exporter
textfile-collector pattern), so kube-side and serving-side telemetry
share one metrics surface instead of two bespoke formats.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import urllib.request

import yaml

from walkai_nos_tpu.cmd import _common
from walkai_nos_tpu.obs.metrics import Registry
from walkai_nos_tpu.utils.quantity import parse_quantity

logger = logging.getLogger("metricsexporter")


def build_metrics(raw: dict, kube=None) -> dict:
    """Metrics schema (`metrics.go:24-42` analogue). If a kube client is
    given, enrich with live node inventory like the reference does."""
    metrics = {
        "installation_uuid": raw.get("installationUUID", ""),
        "chart_values": raw.get("chartValues", {}),
        "components": raw.get("components", {}),
        "nodes": raw.get("nodes", []),
    }
    if kube is not None:
        try:
            nodes = []
            for node in kube.list("Node"):
                meta = node.get("metadata") or {}
                status = node.get("status") or {}
                nodes.append(
                    {
                        "name": meta.get("name", ""),
                        "labels": meta.get("labels") or {},
                        "capacity": status.get("capacity") or {},
                    }
                )
            metrics["nodes"] = nodes
        except Exception as e:
            # The hook pod may run with a low-privilege SA (RBAC denies
            # node lists); the chart-rendered inventory in `raw` stands.
            logger.warning("node inventory unavailable: %s", e)
    return metrics


def registry_from_metrics(metrics: dict) -> Registry:
    """The install payload as `nos_install_*` gauges on the unified
    registry (names/types declared in `obs/catalog.py`, documented in
    docs/observability.md, linted by `make metrics-lint`)."""
    reg = Registry()
    reg.gauge(
        "nos_install_info", "Install identity (value is always 1)"
    ).set(
        1,
        {"installation_uuid": metrics.get("installation_uuid", "")},
    )
    comp = reg.gauge(
        "nos_install_component_enabled",
        "1 if the chart component is enabled, else 0",
    )
    for name, enabled in sorted(
        (metrics.get("components") or {}).items()
    ):
        comp.set(1 if enabled else 0, {"component": str(name)})
    nodes = metrics.get("nodes") or []
    reg.gauge(
        "nos_install_nodes", "Nodes in the install inventory"
    ).set(len(nodes))
    cap = reg.gauge(
        "nos_install_node_capacity",
        "Node capacity by resource, parsed from the Kube quantity",
    )
    for node in nodes:
        for resource, raw in sorted((node.get("capacity") or {}).items()):
            try:
                value = parse_quantity(raw)
            except (TypeError, ValueError):
                continue  # unparseable quantity: skip the series
            cap.set(
                value,
                {"node": node.get("name", ""), "resource": resource},
            )
    return reg


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="metricsexporter")
    parser.add_argument("--metrics-file", required=True)
    parser.add_argument(
        "--endpoint", default="https://telemetry.walkai.io/v1/nos-metrics"
    )
    parser.add_argument(
        "--prom-file",
        default=None,
        help="also write the install inventory as Prometheus text "
        "exposition to this path (textfile-collector pattern)",
    )
    parser.add_argument("--log-level", default="info")
    args = parser.parse_args(argv)
    _common.setup_logging(args.log_level)

    # Exit 0 on every failure (`metricsexporter.go:33-91`).
    try:
        with open(args.metrics_file) as f:
            raw = yaml.safe_load(f) or {}
    except Exception as e:
        logger.warning("cannot read metrics file: %s", e)
        return 0
    kube = None
    try:
        kube = _common.build_kube_client()
    except Exception:
        pass
    try:
        metrics = build_metrics(raw, kube)
    except Exception as e:
        logger.warning("cannot build metrics: %s", e)
        return 0
    if args.prom_file:
        # Exposition failure must not block the POST (and vice versa):
        # both sinks are best-effort, every path still exits 0.
        try:
            with open(args.prom_file, "w") as f:
                f.write(registry_from_metrics(metrics).render())
            logger.info("prometheus exposition written: %s", args.prom_file)
        except Exception as e:
            logger.warning("cannot write prom file: %s", e)
    try:
        req = urllib.request.Request(
            args.endpoint,
            data=json.dumps(metrics).encode(),
            method="POST",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10.0) as resp:
            resp.read()
        logger.info("install metrics sent")
    except Exception as e:
        logger.warning("cannot send metrics: %s", e)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Quota-aware TPU scheduler (restores the deleted `nos-scheduler` binary).

The reference fork removed the scheduler + capacity-scheduling plugin,
keeping only its args type (`pkg/api/scheduler/v1beta3/types.go:26-30`) and
docs. This binary restores the capability for TPU resources: it schedules
pods that set `schedulerName: walkai-nos-scheduler`, applying

1. elastic-quota pre-filter (max limit + borrowing availability),
2. node fit over free `walkai.io/tpu-*` / `google.com/tpu` resources,
3. fair-sharing preemption of over-quota pods when denied capacity,

and binds with the pods/binding subresource (spec.nodeName patch on fakes).
It also runs the capacity labeler (in-quota/over-quota, `key-concepts.md:9-25`)
and keeps ElasticQuota `status.used` current.
"""

from __future__ import annotations

import argparse
import logging
import sys

from walkai_nos_tpu.cmd import _common
from walkai_nos_tpu.kube import objects
from walkai_nos_tpu.kube.client import (
    ApiError,
    EvictionBlocked,
    KubeClient,
    NotFound,
)
from walkai_nos_tpu.kube.runtime import Controller, Manager, Request, Result
from walkai_nos_tpu.quota.fit import (
    fits_node,
    matches_node_affinity,
    satisfies_pod_affinity,
    tolerates_node_taints,
)
from walkai_nos_tpu.quota.labeler import (
    LABEL_CAPACITY,
    CapacityLabeler,
    list_quota_objects,
)
from walkai_nos_tpu.quota.reconciler import QuotaReconciler
from walkai_nos_tpu.quota.scheduler import CapacityScheduling
from walkai_nos_tpu.quota.state import ClusterQuotaState

logger = logging.getLogger("tpuscheduler")

SCHEDULER_NAME = "walkai-nos-scheduler"


def bind_pod(kube: KubeClient, pod: dict, node_name: str) -> None:
    kube.bind_pod(objects.name(pod), objects.namespace(pod) or "default", node_name)


class Scheduler:
    def __init__(self, kube: KubeClient, scheduler_name: str = SCHEDULER_NAME):
        self._kube = kube
        self._name = scheduler_name

    def reconcile(self, request: Request) -> Result:
        try:
            pod = self._kube.get("Pod", request.name, request.namespace or "default")
        except NotFound:
            return Result()
        if (pod.get("spec") or {}).get("schedulerName") != self._name:
            return Result()
        if objects.pod_is_scheduled(pod):
            return Result()
        if (pod.get("status") or {}).get("phase") in ("Succeeded", "Failed"):
            return Result()

        pods = self._kube.list("Pod")
        state = ClusterQuotaState.build(list_quota_objects(self._kube), pods)
        plugin = CapacityScheduling(state)

        decision = plugin.pre_filter(pod)
        if not decision.allowed:
            logger.info(
                "pod %s/%s quota-denied: %s",
                request.namespace,
                request.name,
                decision.reason,
            )
            if decision.borrowing_denied:
                # The borrowing pool is exhausted by other quotas'
                # over-quota pods; fair-share preemption can reclaim this
                # pod's min+guaranteed entitlement (the docs' worked
                # example, `key-concepts.md:31-46`). No node-locality
                # (evictions anywhere shrink others' borrowing), and only
                # the shortfall's worth of chips — not the full request.
                if self._preempt(
                    plugin, pod, pods, request,
                    needed_chips=decision.shortfall,
                ):
                    return Result(requeue_after=0.5)
            # Quota denials are NOT capacity problems: retiling can't
            # create quota headroom, so don't mark Unschedulable (the
            # partitioner would churn slices for a quota-blocked pod).
            return Result(requeue_after=5.0)

        nodes = self._kube.list("Node")
        nodes_by_name = {objects.name(n): n for n in nodes}
        for node in self._gang_aware_order(pod, nodes):
            if not self._node_eligible(pod, node, pods, nodes_by_name):
                continue
            if fits_node(pod, node, pods):
                bind_pod(self._kube, pod, objects.name(node))
                logger.info(
                    "bound %s/%s to %s",
                    request.namespace,
                    request.name,
                    objects.name(node),
                )
                return Result()

        # Physically unschedulable (PostFilter): fair-sharing preemption of
        # over-quota pods elsewhere (`key-concepts.md:31-40`), chosen
        # node-locally so the freed chips are actually usable.
        if self._preempt(plugin, pod, pods, request, nodes=nodes):
            return Result(requeue_after=0.5)  # re-fit after evictions
        # No fit anywhere: record the Unschedulable condition so the
        # partitioner considers re-tiling for this pod — kube-scheduler
        # writes this for its own pods, but ignores foreign-scheduler
        # pods, so WE are the only writer for ours.
        self._mark_unschedulable(pod, request)
        return Result(requeue_after=5.0)  # the partitioner may now retile

    # ---------------------------------------------------------------- helpers

    def _preempt(
        self,
        plugin: CapacityScheduling,
        pod: dict,
        pods: list[dict],
        request: Request,
        nodes: list[dict] | None = None,
        needed_chips: int | None = None,
    ) -> int:
        """Select and evict victims, re-selecting around refusals.

        Eviction goes through the Eviction API: graceful deletion with
        the victim's own terminationGracePeriodSeconds (server default
        when unset) and PodDisruptionBudgets respected. A budget-blocked
        victim survives and is excluded from the next selection round,
        so an unprotected alternative (if any) is still found instead of
        hot-requeuing against the same protected pod forever. Returns
        the number of evictions that actually succeeded — zero means no
        progress, and the caller falls through to its no-victim path
        (unschedulable condition / slow requeue)."""
        excluded: set[tuple[str, str]] = set()
        evicted = 0
        while True:
            victims = plugin.find_preemption_victims(
                pod, pods, nodes, needed_chips, exclude=excluded
            )
            if not victims:
                return evicted
            evicted_this_round = 0
            blocked_this_round = 0
            for victim in victims:
                ns = objects.namespace(victim) or "default"
                logger.info(
                    "preempting over-quota pod %s/%s for %s/%s",
                    ns, objects.name(victim),
                    request.namespace, request.name,
                )
                grace = (victim.get("spec") or {}).get(
                    "terminationGracePeriodSeconds"
                )
                try:
                    self._kube.evict_pod(
                        objects.name(victim), ns,
                        grace_period_seconds=grace,
                    )
                    evicted += 1
                    evicted_this_round += 1
                except EvictionBlocked as e:
                    logger.info(
                        "victim %s/%s protected by a disruption budget, "
                        "skipped: %s",
                        ns, objects.name(victim), e.message,
                    )
                    excluded.add((ns, objects.name(victim)))
                    blocked_this_round += 1
                except NotFound:
                    evicted += 1  # already gone: capacity freed anyway
                    evicted_this_round += 1
                except ApiError as e:
                    # An eviction the API server refuses for any other
                    # reason (403 from missing pods/eviction RBAC, 500,
                    # admission webhook...) must not abort the whole
                    # reconcile: skip this victim and let re-selection
                    # find an alternative, as for a budget block.
                    logger.warning(
                        "evicting %s/%s failed (%s), skipped",
                        ns, objects.name(victim), e,
                    )
                    excluded.add((ns, objects.name(victim)))
                    blocked_this_round += 1
            if blocked_this_round == 0:
                return evicted
            if evicted_this_round > 0:
                # Partial progress invalidates the pod/quota snapshot
                # this selection ran on; re-selecting against it could
                # pile victims on a second node for capacity the first
                # round already half-freed. Stop here — the caller
                # requeues shortly and re-plans against fresh state.
                return evicted

    def _mark_unschedulable(self, pod: dict, request: Request) -> None:
        if objects.pod_is_unschedulable(pod):
            return  # already recorded; don't churn the object
        # Merge-patch replaces lists wholesale, so carry every OTHER
        # condition through and only swap PodScheduled.
        conditions = [
            c
            for c in (pod.get("status") or {}).get("conditions") or []
            if c.get("type") != "PodScheduled"
        ]
        conditions.append(
            {
                "type": "PodScheduled",
                "status": "False",
                "reason": "Unschedulable",
                "message": "no TPU capacity within quota",
            }
        )
        self._kube.patch_status(
            "Pod",
            objects.name(pod),
            {"status": {"conditions": conditions}},
            objects.namespace(pod) or "default",
        )

    def _gang_aware_order(self, pod: dict, nodes: list[dict]) -> list[dict]:
        """Node order for the first-fit bind loop: name order, EXCEPT
        for pods requesting multi-host pool profiles, where gang pods
        should fill the hosts of one pool-slice instance before touching
        another. Pool-share instances are contiguous host-grid blocks
        (`tpu/tiling/pool.py`), so a free share GRID-ADJACENT to a used
        share of the same profile is its instance-mate: order pool
        members by Manhattan distance to the nearest used share in
        their pool, then pools with no consumption, then everything
        else. Exact with one in-flight gang per pool; a placement-aware
        gang scheduler is the strict upgrade."""
        from walkai_nos_tpu.tpu.tiling.pool import (
            is_pool_profile,
            member_grid_info,
        )
        from walkai_nos_tpu.tpu.tiling.profile import get_requested_profiles

        by_name = sorted(nodes, key=objects.name)
        wanted = get_requested_profiles(pod)
        if not wanted:
            return by_name
        # Pool-member geometry via the shared mapping (pool.py — the
        # planner and this ordering must agree on instance layout).
        infos: dict[str, tuple[str, tuple[int, ...], set[str]]] = {}
        pool_wanted: set[str] = set()
        wanted_by_chips: dict[int, set[str]] = {}
        for n in nodes:
            info = member_grid_info(
                objects.labels(n), objects.annotations(n)
            )
            if info is None:
                continue
            key, coord, used, topo = info
            infos[objects.name(n)] = (key, coord, used)
            per_host = topo.model.chips_per_host
            if per_host not in wanted_by_chips:
                wanted_by_chips[per_host] = {
                    p for p in wanted if is_pool_profile(p, topo)
                }
            pool_wanted.update(wanted_by_chips[per_host])
        if not pool_wanted:
            return by_name
        used_coords: dict[str, list[tuple[int, ...]]] = {}
        for key, coord, used in infos.values():
            if pool_wanted & used:
                used_coords.setdefault(key, []).append(coord)

        def sort_key(n):
            name = objects.name(n)
            info = infos.get(name)
            if info is None:
                return (2, 0, name)  # cannot hold a pool share anyway
            key, coord, _used = info
            anchors = used_coords.get(key)
            if anchors:
                dist = min(
                    sum(abs(a - b) for a, b in zip(coord, anchor))
                    for anchor in anchors
                )
                return (0, dist, name)
            return (1, 0, name)

        return sorted(nodes, key=sort_key)

    def _node_eligible(
        self, pod: dict, node: dict, pods: list[dict],
        nodes_by_name: dict[str, dict],
    ) -> bool:
        """The scheduler-framework gates kube-scheduler would apply:
        cordon, readiness, nodeSelector, taints/tolerations, required
        node affinity, and required pod (anti)affinity (`quota/fit.py`)."""
        if (node.get("spec") or {}).get("unschedulable"):
            return False
        for cond in (node.get("status") or {}).get("conditions") or []:
            if cond.get("type") == "Ready" and cond.get("status") != "True":
                return False
        selector = (pod.get("spec") or {}).get("nodeSelector") or {}
        labels = objects.labels(node)
        if not all(labels.get(k) == v for k, v in selector.items()):
            return False
        return (
            tolerates_node_taints(pod, node)
            and matches_node_affinity(pod, node)
            and satisfies_pod_affinity(pod, node, pods, nodes_by_name)
        )


def build_manager(kube: KubeClient, scheduler_name: str = SCHEDULER_NAME) -> Manager:
    from walkai_nos_tpu.kube.sharedwatch import SharedWatchClient

    # Two controllers watch Pods (scheduler + capacity labeler); the
    # shared-watch decorator gives them one upstream stream per kind,
    # the informer property controller-runtime's manager provides. The
    # manager owns it: pump threads stop with the manager.
    kube = SharedWatchClient(kube)
    manager = Manager()
    manager.own(kube)
    manager.add(
        Controller(
            "tpu-scheduler",
            kube,
            "Pod",
            Scheduler(kube, scheduler_name).reconcile,
            max_concurrent=1,  # serialized decisions, like the partitioner
        )
    )
    def _labeler_relevant(event: str, obj, old) -> bool:
        """The labeler's answer only changes when a pod starts/stops
        holding quota or moves: gate MODIFIED on phase / nodeName /
        capacity-label changes so status heartbeats across the whole
        cluster don't each trigger an O(pods) relabel sweep."""
        if event != "MODIFIED" or old is None:
            return True
        def view(p):
            return (
                (p.get("status") or {}).get("phase"),
                (p.get("spec") or {}).get("nodeName"),
                objects.labels(p).get(LABEL_CAPACITY),
            )
        return view(obj) != view(old)

    manager.add(
        Controller(
            "capacity-labeler",
            kube,
            "Pod",
            CapacityLabeler(kube).reconcile,
            predicates=[_labeler_relevant],
        )
    )
    # Quota reconcile loops keyed on the QUOTA objects (the upstream
    # operator's role): status + labels stay fresh with zero pods and no
    # scheduling activity.
    for kind, name in (
        ("ElasticQuota", "elasticquota-reconciler"),
        ("CompositeElasticQuota", "compositeelasticquota-reconciler"),
    ):
        manager.add(
            Controller(
                name,
                kube,
                kind,
                QuotaReconciler(kube, kind).reconcile,
            )
        )
    return manager


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="tpuscheduler")
    parser.add_argument("--scheduler-name", default=SCHEDULER_NAME)
    parser.add_argument("--health-probe-addr", default=":8081")
    parser.add_argument("--metrics-addr", default=":8080")
    parser.add_argument("--leader-elect", action="store_true")
    parser.add_argument("--log-level", default="info")
    args = parser.parse_args(argv)
    _common.setup_logging(args.log_level)

    kube = _common.build_kube_client()
    health = _common.start_health(args.health_probe_addr, args.metrics_addr)
    manager = build_manager(kube, args.scheduler_name)
    stop = _common.wait_for_shutdown()

    if args.leader_elect:
        from walkai_nos_tpu.kube.leader import LeaderElector

        elector = LeaderElector(
            kube,
            "tpuscheduler-leader",
            namespace=_common.current_namespace(),
            on_started_leading=manager.start,
            on_stopped_leading=manager.stop,
        )
        elector.start()
        health.mark_ready()
        stop.wait()
        elector.stop()
    else:
        manager.start()
        health.mark_ready()
        stop.wait()
        manager.stop()
    health.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Per-node TPU agent DaemonSet main (`cmd/migagent/migagent.go:56-199`).

Requires NODE_NAME. Startup mirrors `initAgent` (:165): verify the host has
at least one TPU chip (`checkAtLeastOneMigGpu` analogue, :179), then clean
up slices no pod is using that aren't reachable from the kubelet's
allocatable set (`cleanupUnusedMigResources`, :192). Runs the
Reporter/Actuator pair on this node's watch with the SharedState handshake,
plus the device-plugin manager advertising `walkai.io/tpu-<shape>`.

Device layer selection (the build-tag dual at runtime): native libtpudev
when present; `WALKAI_TPUDEV_FAKE=<mesh>` runs the in-memory fake for
kind-cluster demos; otherwise the stub makes startup fail loudly.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

from walkai_nos_tpu.api import constants
from walkai_nos_tpu.cmd import _common
from walkai_nos_tpu.config import AgentConfig, load_config
from walkai_nos_tpu.controllers.tpuagent.actuator import Actuator
from walkai_nos_tpu.controllers.tpuagent.reporter import Reporter
from walkai_nos_tpu.controllers.tpuagent.shared import SharedState
from walkai_nos_tpu.kube import predicates
from walkai_nos_tpu.kube.runtime import Controller, Manager
from walkai_nos_tpu.tpu import topology
from walkai_nos_tpu.tpu.errors import TpuError
from walkai_nos_tpu.tpu.tiling.client import DevicePluginClient, TilingClient

logger = logging.getLogger("tpuagent")


def build_tpudev():
    fake_mesh = os.environ.get("WALKAI_TPUDEV_FAKE")
    if fake_mesh:
        from walkai_nos_tpu.tpudev.fake import FakeTpudevClient

        logger.warning("using FAKE tpudev with mesh %s", fake_mesh)
        return FakeTpudevClient(mesh=topology.parse_shape(fake_mesh))
    from walkai_nos_tpu.tpudev.native import load_client

    return load_client()


def init_agent(tiling_client: TilingClient) -> None:
    """Startup checks (`initAgent`, `cmd/migagent/migagent.go:165-199`)."""
    host = tiling_client.get_topology()  # raises on stub/no chips
    if host.chip_count < 1:
        raise TpuError("no TPU chips on this host")
    logger.info(
        "host mesh %s with %d chips",
        topology.format_shape(host.mesh),
        host.chip_count,
    )
    used = tiling_client.get_tpu_devices().get_used()
    deleted = tiling_client.delete_all_except(used)
    if deleted:
        logger.info("startup cleanup removed orphan slices: %s", deleted)


def build_manager(
    kube,
    tiling_client: TilingClient,
    plugin_client: DevicePluginClient,
    node_name: str,
    config: AgentConfig,
) -> tuple[Manager, SharedState]:
    from walkai_nos_tpu.kube.sharedwatch import SharedWatchClient

    # Reporter and Actuator both watch the agent's Node: share one
    # upstream stream (informer semantics), owned by the manager.
    kube = SharedWatchClient(kube)
    shared = SharedState()
    manager = Manager()
    manager.own(kube)
    manager.add(
        Controller(
            constants.AGENT_REPORTER_NAME,
            kube,
            "Node",
            Reporter(
                kube,
                tiling_client,
                shared,
                node_name,
                refresh_interval=config.report_interval_s,
            ).reconcile,
            predicates=[
                predicates.matching_name(node_name),
                predicates.exclude_delete(),
            ],
        )
    )
    manager.add(
        Controller(
            constants.AGENT_ACTUATOR_NAME,
            kube,
            "Node",
            Actuator(
                kube, tiling_client, plugin_client, shared, node_name
            ).reconcile,
            predicates=[
                predicates.matching_name(node_name),
                predicates.exclude_delete(),
                predicates.annotations_changed(),
            ],
        )
    )
    return manager, shared


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="tpuagent")
    parser.add_argument("--config", help="TpuAgentConfig YAML path")
    parser.add_argument("--log-level", default="info")
    parser.add_argument(
        "--pod-resources-socket", default=constants.POD_RESOURCES_SOCKET
    )
    args = parser.parse_args(argv)
    _common.setup_logging(args.log_level)

    node_name = os.environ.get(constants.ENV_NODE_NAME)
    if not node_name:
        logger.error("%s env var is required", constants.ENV_NODE_NAME)
        return 1

    config = (
        load_config(args.config, "TpuAgentConfig") if args.config else AgentConfig()
    )

    tpudev = build_tpudev()
    from walkai_nos_tpu.resource.lister import PodResourcesClient

    resources = PodResourcesClient(args.pod_resources_socket)
    tiling_client = TilingClient(resources, tpudev)
    try:
        init_agent(tiling_client)
    except TpuError as e:
        logger.error("startup check failed: %s", e)
        return 1

    kube = _common.build_kube_client()
    plugin_client = DevicePluginClient(kube)
    health = _common.start_health(
        config.manager.health_probe_addr, config.manager.metrics_addr
    )

    from walkai_nos_tpu.deviceplugin import PluginManager, pool_worker_source

    # Pool shares are served with the multi-host worker env merged in
    # (worker id / hostnames / coordinator from the pool labels), so a
    # gang's JAX processes bootstrap straight from their Allocate env.
    plugins = PluginManager(
        None, source=pool_worker_source(tpudev.list_slices, kube, node_name)
    )
    plugins.start()

    manager, _shared = build_manager(
        kube, tiling_client, plugin_client, node_name, config
    )
    stop = _common.wait_for_shutdown()
    manager.start()
    health.mark_ready()
    stop.wait()
    manager.stop()
    plugins.stop()
    health.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""serverouter: the fleet-router front-end binary (ROADMAP item 4).

One process that owns a fleet of serving replicas behind a single
HTTP surface — the piece that finally puts inference traffic through
the partitioner-adjacent serving stack as a FLEET instead of a single
engine:

- **HTTP replica mode** (`--replica URL`, repeatable): each URL is a
  demo-server pod (`demos/tpu-sharing-comparison/app/main.py`) on its
  own TPU slice; the router fronts them over their existing
  `/generate` + `/healthz` + `/stats` endpoints
  (`router/replica.HttpReplica`) — the real-deployment shape.
- **In-process mode** (`--inproc N`): N tiny `ContinuousBatcher`
  replicas in this process — CI, demos, and single-host smoke runs;
  `--spares K` keeps K warmed standbys in a `RespawningSliceProvider`
  so the autoscaling reconciler can admit them under load (released
  standbys are rebuilt during the idle window that triggered the
  scale-down, so capacity never ratchets away).

Endpoints (the router's own, on `--port`):

- `POST /generate`  {"prompt": [...], "max_new_tokens"?, "eos_id"?,
  "temperature"?, "top_k"?, "top_p"?, "seed"?} -> the routed
  replica's tokens + timing + which replica served it + the
  request's cross-process `trace_id` (also the `X-Walkai-Trace`
  response header; a well-formed client-supplied header is adopted).
  Routing is prefix-affinity with a power-of-two-choices load
  fallback (`router/core.py`, docs/serving-router.md).
- `GET /healthz` -> {"ok": bool, "fleet": ...} — the driver thread's
  latest `router.stats()` snapshot: replica membership/drain
  lifecycle, per-replica scale signals + anomaly verdicts + scrape
  health, fleet prefix hit rate, scale-event tallies.
- `GET /metrics` -> Prometheus exposition of the ROUTER registry
  (the `router_*` series) PLUS every replica's engine series
  federated under a `replica` label (`obs/federation.py`) — one
  scrape for the whole fleet's `cb_*` telemetry.
- `GET /debug/trace` -> the merged fleet timeline: router
  route/queue/round-trip spans + every replica's Chrome trace
  export, clock-aligned into one Perfetto-loadable JSON.
- `GET /debug/flight` -> the flight recorder's bounded on-disk ring
  of anomaly/SLO-breach bundles (`obs/anomaly.py`).
- `GET/POST /debug/capture`, `GET /debug/capture/download` -> the
  fleet capture plane's status / rotate / download
  (`WALKAI_CAPTURE_DIR` arms it; `obs/capture.py`, done records name
  the routed replica).
- `GET /debug/canary` -> the shadow/canary plane's status: gate
  (digest_exact vs latency_only), mirrored/compared/divergence
  counters, verdict state + reason, windowed latency deltas, and the
  first divergence's coordinates + flight-bundle path (404 until a
  canary is armed).

Canary knobs (`--canary-*`, env `WALKAI_CANARY_*`): `--canary` arms
an in-process candidate replica built from the same weights under
`--canary-override KEY=VALUE` engine knobs (repeatable;
`WALKAI_CANARY_OVERRIDES` comma-separates them), `--canary-replica
URL` registers a remote pod as the canary in HTTP mode, and
`--canary-mirror` sets the sampled mirror fraction (default 1.0).

A single driver thread owns the fleet (the same one-owner discipline
as the demo server's cb_driver): it drains submissions, steps every
replica, ticks the autoscaling reconciler, and fulfils waiters — so
the router needs no locking around engine state, and reconcile ticks
keep flowing while idle (that's when scale-DOWN happens).

Env knobs (in-process mode): WALKAI_ROUTER_LM_MODEL (tiny|small,
default tiny), WALKAI_ROUTER_SLOTS (default 4), WALKAI_ROUTER_VOCAB /
WALKAI_ROUTER_SEQ (test seams, like the demo server's WALKAI_LM_*).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from walkai_nos_tpu.obs.router import RouterObs
from walkai_nos_tpu.obs.trace import valid_trace_id
from walkai_nos_tpu.router.autoscale import ScalePolicy
from walkai_nos_tpu.router.core import FleetRouter
from walkai_nos_tpu.router.replica import HttpReplica

logger = logging.getLogger("serverouter")


class RespawningSliceProvider:
    """The long-running binary's provider: up to `spares` warmed
    standby replicas, REBUILT after release. A drained engine is
    one-way (it can never serve again), so the static CI provider
    would ratchet a diurnal fleet down to min_replicas for good —
    every idle-period scale-down permanently eating one slice of
    capacity. Instead, `release()` builds and warms a fresh standby
    from the factory right away: release fires when the fleet is
    IDLE by definition (that's what triggered the drain), so the
    standby's XLA warm-up lands in the idle window, not in the surge
    that later acquires it."""

    def __init__(self, factory, spares: int):
        self._factory = factory
        self._cap = spares
        self._seq = 0
        self._pool = [self._build() for _ in range(spares)]

    def _build(self):
        replica = self._factory(f"spare{self._seq}")
        self._seq += 1
        replica.warm()
        return replica

    def acquire(self):
        return self._pool.pop(0) if self._pool else None

    def release(self, replica) -> None:
        # The retired replica is dropped, not retained: a drained
        # engine can never serve again, and holding it would leak one
        # full KV-cache pool per diurnal scale-down cycle in a
        # long-running process.
        if len(self._pool) < self._cap:
            self._pool.append(self._build())


def build_inproc_replicas(n: int, *, slots: int | None = None):
    """N in-process engine replicas sharing one tiny weight set (the
    CI / smoke shape; a production fleet uses HTTP replicas on real
    slices). Imports jax lazily so `--help` and the HTTP-mode path
    never pay for it."""
    import jax

    from walkai_nos_tpu.models.lm import LM_SMALL, LM_TINY, DecoderLM
    from walkai_nos_tpu.models.serve import ContinuousBatcher
    from walkai_nos_tpu.router.replica import EngineReplica

    cfg = (
        LM_SMALL
        if os.environ.get("WALKAI_ROUTER_LM_MODEL") == "small"
        else LM_TINY
    )
    if os.environ.get("WALKAI_ROUTER_VOCAB") or os.environ.get(
        "WALKAI_ROUTER_SEQ"
    ):
        import dataclasses

        cfg = dataclasses.replace(
            cfg,
            vocab_size=int(
                os.environ.get("WALKAI_ROUTER_VOCAB")
                or cfg.vocab_size
            ),
            max_seq_len=int(
                os.environ.get("WALKAI_ROUTER_SEQ") or cfg.max_seq_len
            ),
        )
    slots = slots or int(os.environ.get("WALKAI_ROUTER_SLOTS", "4"))
    params = jax.device_put(
        DecoderLM(cfg).init_params(jax.random.PRNGKey(0))
    )

    def factory(name: str, **engine_kwargs):
        # Extra engine kwargs are the canary seam: the candidate
        # replica shares the fleet's weights and config but takes
        # `--canary-override` knobs (ENGINE_KNOBS axes only).
        return EngineReplica(
            ContinuousBatcher(
                cfg, params, slots=engine_kwargs.pop("slots", slots),
                **engine_kwargs,
            ),
            name=name,
        )

    return cfg, factory


class RouterDriver:
    """The one thread that owns the fleet: submissions in, finished
    records out, one `router.step()` per turn (replica advance +
    reconciler tick) — idle turns still tick, on a short timeout, so
    scale-down proceeds when traffic stops."""

    def __init__(self, router: FleetRouter, *, idle_tick_s: float = 0.05):
        self.router = router
        self.alive = True
        self._idle_tick_s = idle_tick_s
        self._queue: queue.Queue = queue.Queue()
        self._waiters: dict[int, dict] = {}
        self._stop = threading.Event()
        # Fleet-stats snapshot, refreshed by the driver thread each
        # turn and swapped in whole: HTTP handler threads read THIS,
        # never router.stats() directly — the router is single-
        # driver-threaded (a concurrent stats() would race the
        # reconciler's retire() over the handle list and, in HTTP
        # mode, run synchronous health probes on the handler thread).
        self._fleet_stats = router.stats()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="router-driver"
        )
        self._thread.start()

    def fleet_stats(self) -> dict:
        """The driver's latest whole-snapshot of `router.stats()` —
        at most one idle tick stale, safe from any thread."""
        return self._fleet_stats

    def submit(
        self, prompt, max_new_tokens, knobs: dict,
        trace_id: str | None = None,
    ) -> dict:
        holder = {
            "done": threading.Event(),
            # The enqueue time becomes the router trace's queue-wait
            # span (enqueue -> the driver's submit pick-up); the
            # trace id (client-supplied or router-minted) comes back
            # on the completion record.
            "enqueued_at": time.monotonic(),
            "trace_id_in": trace_id,
        }
        self._queue.put((prompt, max_new_tokens, knobs, holder))
        return holder

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)

    def _fail(
        self, holder: dict, error: str, *, client: bool = False
    ) -> None:
        """`client=True` marks a request the CALLER got wrong (bad
        knobs, oversize prompt — a 400); everything else (fleet
        empty, driver death, replica failure) is a server-side 503 a
        client should retry."""
        holder["error"] = error
        holder["client_error"] = client
        holder["tokens"] = None
        holder["done"].set()

    def _loop(self) -> None:
        router = self.router
        try:
            while not self._stop.is_set():
                # Spin only while some replica needs step() to make
                # progress (in-process engines). HTTP replicas' work
                # advances remotely — their records arrive via worker
                # threads and are collected on the timeout tick, so a
                # pure-HTTP fleet must NOT busy-loop for the length of
                # every remote generation.
                stepping = any(
                    getattr(r, "steps_locally", True) and r.has_work
                    for r in router.replicas
                )
                try:
                    item = self._queue.get(
                        block=not stepping,
                        timeout=self._idle_tick_s,
                    )
                    while True:
                        prompt, max_new, knobs, holder = item
                        try:
                            rid = router.submit(
                                prompt, max_new_tokens=max_new,
                                trace_id=holder.get("trace_id_in"),
                                enqueued_at=holder.get("enqueued_at"),
                                **knobs,
                            )
                        except ValueError as bad:
                            # Replica-side validation: the CALLER's
                            # error — fail that request with a 400.
                            self._fail(holder, str(bad), client=True)
                        except RuntimeError as unplaced:
                            # Fleet-side condition (no active
                            # replica mid-scale-in): retryable 503.
                            self._fail(holder, str(unplaced))
                        else:
                            self._waiters[rid] = holder
                        item = self._queue.get_nowait()
                except queue.Empty:
                    pass
                router.step()
                for rid, rec in router.drain_done_records().items():
                    waiter = self._waiters.pop(rid, None)
                    if waiter is None:
                        continue
                    waiter.update(rec)
                    waiter["done"].set()
                self._fleet_stats = router.stats()
        except Exception as e:  # noqa: BLE001 — fleet-driver death
            self.alive = False
            logger.exception("router driver failed: %r", e)
            for holder in self._waiters.values():
                self._fail(holder, "router driver failed")
            self._waiters.clear()
            while True:
                try:
                    *_, holder = self._queue.get_nowait()
                except queue.Empty:
                    break
                self._fail(holder, "router driver failed")


def make_handler(driver: RouterDriver, obs: RouterObs):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def do_POST(self):  # noqa: N802 (http.server API)
            if self.path == "/debug/capture":
                cap = driver.router.capture
                if cap is None:
                    self.send_error(
                        404, "no capture armed (set WALKAI_CAPTURE_DIR)"
                    )
                    return
                from walkai_nos_tpu.obs.capture import (
                    rotate_action_from_body,
                )

                n = int(self.headers.get("Content-Length", 0))
                try:
                    rotate_action_from_body(self.rfile.read(n))
                except (TypeError, ValueError) as e:
                    self.send_error(400, str(e))
                    return
                cap.rotate()
                self._json(
                    200, {"fleet": driver.router.capture_stats()}
                )
                return
            if self.path != "/generate":
                self.send_error(404)
                return
            # Client-supplied trace id (X-Walkai-Trace): adopted when
            # well-formed so a caller can correlate its own logs with
            # /debug/trace; anything else is ignored and the router
            # mints one (`obs/trace.valid_trace_id` — the one charset
            # contract the demo server shares).
            trace_in = valid_trace_id(
                self.headers.get("X-Walkai-Trace")
            )
            n = int(self.headers.get("Content-Length", 0))
            try:
                body = json.loads(self.rfile.read(n) or b"{}")
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
                prompt = body.get("prompt")
                if not isinstance(prompt, list) or not prompt:
                    raise ValueError("prompt must be a non-empty list")
                max_new = int(body.get("max_new_tokens", 16))
                knobs = {}
                for key, cast in (
                    ("eos_id", int), ("temperature", float),
                    ("top_k", int), ("top_p", float), ("seed", int),
                ):
                    if body.get(key) is not None:
                        knobs[key] = cast(body[key])
            except (TypeError, ValueError) as e:
                self.send_error(400, str(e))
                return
            t0 = time.perf_counter()
            holder = driver.submit(
                prompt, max_new, knobs, trace_id=trace_in
            )
            while not holder["done"].wait(timeout=1.0):
                if not driver.alive:
                    self.send_error(503, "router driver failed; retry")
                    return
                if time.perf_counter() - t0 > 120.0:
                    self.send_error(503, "generation timed out")
                    return
            if holder.get("tokens") is None:
                # Only CALLER mistakes are 400s; capacity and
                # failure conditions are retryable 503s (a remote
                # replica's error record has no client_error mark).
                self.send_error(
                    400 if holder.get("client_error") else 503,
                    holder.get("error") or "generation failed",
                )
                return
            trace_id = holder.get("trace_id")
            self._json(200, {
                "tokens": holder["tokens"],
                "ttft_seconds": round(holder.get("ttft_s") or 0.0, 6),
                "engine_wall_seconds": round(
                    holder.get("wall_s") or 0.0, 6
                ),
                "replica": holder.get("replica"),
                "truncated": holder.get("truncated", False),
                # The request's cross-process trace id: look it up in
                # /debug/trace to see this call's route -> queue ->
                # prefill -> first-token path across processes.
                "trace_id": trace_id,
            }, headers=(
                {"X-Walkai-Trace": trace_id} if trace_id else None
            ))

        def do_GET(self):  # noqa: N802 (http.server API)
            if self.path == "/healthz":
                self._json(200, {
                    "ok": driver.alive,
                    "fleet": driver.fleet_stats(),
                })
            elif self.path == "/metrics":
                # Router registry + every replica's engine series
                # federated under a `replica` label. Safe from a
                # handler thread: the render reads lock-guarded
                # registries and the adapters' cached scrapes only
                # (an HTTP replica past its cache window pays one
                # scrape here — a Prometheus pull, not a routing
                # path; caveats in docs/observability.md).
                data = driver.router.federated_metrics().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            elif self.path == "/debug/trace":
                # The merged fleet timeline (router spans + every
                # replica's Chrome export, clock-aligned) — load it
                # straight into Perfetto.
                self._json(200, driver.router.fleet_trace())
            elif self.path == "/debug/canary":
                # The shadow plane's status, read from the driver's
                # whole-snapshot like /healthz (handler threads never
                # touch live router state): stale by at most one idle
                # tick, which a rollout decision can afford.
                canary = driver.fleet_stats().get("canary")
                if canary is None:
                    self.send_error(
                        404,
                        "no canary armed (--canary / "
                        "--canary-replica / WALKAI_CANARY=1)",
                    )
                    return
                self._json(200, {"canary": canary})
            elif self.path == "/debug/flight":
                flight = driver.router.flight
                self._json(200, {
                    "dir": flight.dir if flight else None,
                    "bundles": flight.bundles() if flight else [],
                })
            elif self.path == "/debug/capture":
                # Fleet capture status (enabled false when
                # WALKAI_CAPTURE_DIR never armed it) — wrapped in
                # "fleet" the way the demo server wraps its payload
                # in "engine" (and /healthz wraps the router stats),
                # so the two binaries' envelopes differ predictably,
                # not silently.
                self._json(
                    200, {"fleet": driver.router.capture_stats()}
                )
            elif self.path == "/debug/capture/download":
                cap = driver.router.capture
                if cap is None:
                    self.send_error(
                        404, "no capture armed (set WALKAI_CAPTURE_DIR)"
                    )
                    return
                data = cap.read_text().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "application/x-ndjson"
                )
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            else:
                self.send_error(404)

        def _json(
            self, code: int, payload: dict, headers: dict | None = None
        ) -> None:
            data = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for key, value in (headers or {}).items():
                self.send_header(key, value)
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, *args):  # quiet
            pass

    return Handler


class RouterServer(ThreadingHTTPServer):
    daemon_threads = True
    request_queue_size = 128


def build(args) -> tuple[RouterDriver, RouterObs]:
    """Fleet + driver from parsed args — the testable seam `main`
    and the tier-1 wiring test share."""
    obs = RouterObs(
        enabled=os.environ.get("WALKAI_OBS", "1") == "1"
    )
    # Fleet-level capture plane: WALKAI_CAPTURE_DIR (+ the shared
    # MAX_BYTES/MAX_FILES bounds — `CaptureLog.from_env`, the ONE
    # env-arming rule the demo server uses too) arms a bounded
    # rotating recorder of routed traffic (prompt/knobs/arrival +
    # completion digests, done records naming the routed replica) —
    # the incident timeline per-replica engine captures are replayed
    # against. Served at /debug/capture like the demo server's.
    from walkai_nos_tpu.obs.capture import CaptureLog

    capture = CaptureLog.from_env()
    if args.replica:
        replicas = [HttpReplica(url) for url in args.replica]
        router = FleetRouter(
            replicas, obs=obs, capture=capture,
            canary_mirror=args.canary_mirror,
        )
        if args.canary_replica:
            router.add_replica(
                HttpReplica(args.canary_replica), role="canary"
            )
    else:
        policy = ScalePolicy(
            min_replicas=(
                1 if args.min_replicas is None else args.min_replicas
            ),
            max_replicas=(
                8 if args.max_replicas is None else args.max_replicas
            ),
        )
        _, factory = build_inproc_replicas(args.inproc)
        replicas = [factory(f"r{i}") for i in range(args.inproc)]
        # Warm every engine before traffic: a cold engine pays its
        # XLA compiles on the first concurrent admissions,
        # mid-traffic. The provider warms its own standbys the same
        # way (and respawns them on release, so idle-period
        # scale-downs don't permanently eat fleet capacity).
        for replica in replicas:
            replica.warm()
        provider = (
            RespawningSliceProvider(factory, args.spares)
            if args.spares > 0 else None
        )
        router = FleetRouter(
            replicas, provider=provider, scale_policy=policy, obs=obs,
            capture=capture, canary_mirror=args.canary_mirror,
        )
        if args.canary or args.canary_override:
            from walkai_nos_tpu.sim.replay import ENGINE_KNOBS

            overrides = dict(args.canary_override)
            bad = sorted(set(overrides) - set(ENGINE_KNOBS))
            if bad:
                raise ValueError(
                    f"--canary-override knob(s) {bad} are not engine "
                    f"knobs; valid axes: {ENGINE_KNOBS}"
                )
            canary = factory("canary0", **overrides)
            canary.warm()
            router.add_replica(canary, role="canary")
    return RouterDriver(router), obs


def parse_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        description="fleet router front-end over serving replicas"
    )
    parser.add_argument(
        "--port", type=int,
        default=int(os.environ.get("PORT", "8090")),
    )
    parser.add_argument(
        "--replica", action="append", default=[],
        help="HTTP replica base URL (repeatable); omit for --inproc",
    )
    parser.add_argument(
        "--inproc", type=int, default=2,
        help="in-process replica count when no --replica is given",
    )
    parser.add_argument(
        "--spares", type=int, default=0,
        help="extra in-process replicas held by the autoscaler "
             "(in-process mode only)",
    )
    parser.add_argument(
        "--min-replicas", type=int, default=None,
        help="autoscaler floor, default 1 (in-process mode only)",
    )
    parser.add_argument(
        "--max-replicas", type=int, default=None,
        help="autoscaler ceiling, default 8 (in-process mode only)",
    )
    from walkai_nos_tpu.cmd.replay import parse_override

    parser.add_argument(
        "--canary", action="store_true",
        default=os.environ.get("WALKAI_CANARY") == "1",
        help="arm an in-process candidate-config canary replica "
             "(in-process mode only; WALKAI_CANARY=1)",
    )
    parser.add_argument(
        "--canary-override", action="append",
        type=parse_override, metavar="KEY=VALUE",
        default=[
            parse_override(item)
            for item in os.environ.get(
                "WALKAI_CANARY_OVERRIDES", ""
            ).split(",") if item.strip()
        ],
        help="canary engine knob override, repeatable (implies "
             "--canary; WALKAI_CANARY_OVERRIDES=k=v,k=v)",
    )
    parser.add_argument(
        "--canary-replica", default=os.environ.get(
            "WALKAI_CANARY_REPLICA"
        ),
        help="HTTP canary pod base URL (HTTP mode only; "
             "WALKAI_CANARY_REPLICA)",
    )
    parser.add_argument(
        "--canary-mirror", type=float,
        default=float(os.environ.get("WALKAI_CANARY_MIRROR", "1.0")),
        help="fraction of live submits mirrored to the canary "
             "(default 1.0; WALKAI_CANARY_MIRROR)",
    )
    args = parser.parse_args(argv)
    if args.replica and (
        args.spares > 0
        or args.min_replicas is not None
        or args.max_replicas is not None
    ):
        # HTTP mode has no slice provider (remote pods own their
        # lifecycle): silently ignoring an autoscaling flag would
        # read as autoscaling-enabled.
        parser.error(
            "--spares/--min-replicas/--max-replicas require "
            "in-process mode (no --replica)"
        )
    if args.replica and (args.canary or args.canary_override):
        # Same no-silent-ignore rule: an in-process canary cannot be
        # built against remote pods' weights — HTTP mode points at a
        # candidate pod instead.
        parser.error(
            "--canary/--canary-override require in-process mode; "
            "use --canary-replica URL with --replica"
        )
    if args.canary_replica and not args.replica:
        parser.error(
            "--canary-replica requires HTTP mode (--replica); "
            "use --canary in-process"
        )
    if not 0.0 <= args.canary_mirror <= 1.0:
        parser.error(
            f"--canary-mirror must be in [0, 1]; "
            f"got {args.canary_mirror}"
        )
    return args


def main(argv=None) -> None:
    from walkai_nos_tpu.cmd import _common

    _common.setup_logging(os.environ.get("LOG_LEVEL", "info"))
    args = parse_args(argv)
    driver, obs = build(args)
    server = RouterServer(
        ("0.0.0.0", args.port), make_handler(driver, obs)
    )
    logger.info(
        "serverouter on :%d fronting %d replica(s)",
        args.port, len(driver.router.replicas),
    )
    threading.Thread(
        target=server.serve_forever, daemon=True, name="router-http"
    ).start()
    _common.wait_for_shutdown().wait()
    server.shutdown()
    driver.stop()


if __name__ == "__main__":
    main()

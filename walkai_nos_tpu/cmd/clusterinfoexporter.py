"""Cluster-info exporter loop (`cmd/clusterinfoexporter/clusterinfoexporter.go:37-133`).

Every --interval seconds: collect the cluster TPU inventory + TPU-pod
summaries and POST the JSON snapshot to --endpoint with an optional Bearer
token. Send failures are logged and skipped — the loop must outlive a flaky
receiver (`sendSnapshot`, :95-128).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import urllib.error
import urllib.request

from walkai_nos_tpu.cmd import _common
from walkai_nos_tpu.clusterinfo import Collector

logger = logging.getLogger("clusterinfoexporter")


def send_snapshot(
    endpoint: str, snapshot: dict, auth_token: str = "", timeout: float = 10.0
) -> None:
    req = urllib.request.Request(
        endpoint,
        data=json.dumps(snapshot).encode(),
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    if auth_token:
        req.add_header("Authorization", f"Bearer {auth_token}")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        resp.read()


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="clusterinfoexporter")
    parser.add_argument("--endpoint", required=True)
    # Flag wins; WALKAI_AUTH_TOKEN env is how the Helm chart injects the
    # token from a Secret without putting it on the command line.
    parser.add_argument(
        "--auth-token", default=os.environ.get("WALKAI_AUTH_TOKEN", "")
    )
    parser.add_argument("--interval", type=float, default=60.0)
    parser.add_argument("--log-level", default="info")
    args = parser.parse_args(argv)
    _common.setup_logging(args.log_level)

    kube = _common.build_kube_client()
    collector = Collector(kube)
    stop = _common.wait_for_shutdown()

    while not stop.is_set():
        try:
            snapshot = collector.collect().to_dict()
            send_snapshot(args.endpoint, snapshot, args.auth_token)
            logger.info(
                "snapshot sent: %d TPUs, %d pods",
                len(snapshot["tpus"]),
                len(snapshot["pods"]),
            )
        except Exception as e:  # the loop must survive any single failure
            logger.warning("snapshot failed: %s", e)
        stop.wait(args.interval)
    return 0


if __name__ == "__main__":
    sys.exit(main())

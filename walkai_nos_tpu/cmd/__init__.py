"""Process entry points (`cmd/` analogue, SURVEY.md §2.1).

Run as modules:
    python -m walkai_nos_tpu.cmd.tpupartitioner --config <yaml>
    python -m walkai_nos_tpu.cmd.tpuagent --config <yaml>
    python -m walkai_nos_tpu.cmd.tpusharingagent --config <yaml>
    python -m walkai_nos_tpu.cmd.clusterinfoexporter --endpoint <url>
    python -m walkai_nos_tpu.cmd.metricsexporter --metrics-file <yaml>
"""

"""No-op stub — the default when the native library isn't present.

Analogue of `pkg/gpu/nvml/client_stub.go:24-58` (`//go:build !nvml`): every
method fails with a clear "tpudev support disabled" error so non-agent
binaries and tests never need the hardware layer.
"""

from __future__ import annotations

from walkai_nos_tpu.tpu.errors import GenericError
from walkai_nos_tpu.tpudev.client import HostTopology, SliceInfo, TpudevClient

_MSG = "tpudev support disabled (native libtpudev not loaded)"


class StubTpudevClient(TpudevClient):
    def get_topology(self) -> HostTopology:
        raise GenericError(_MSG)

    def list_slices(self) -> list[SliceInfo]:
        raise GenericError(_MSG)

    def get_slice_mesh_index(self, slice_id: str) -> int:
        raise GenericError(_MSG)

    def create_slices(self, placements: list) -> list[SliceInfo]:
        raise GenericError(_MSG)

    def delete_slice(self, slice_id: str) -> None:
        raise GenericError(_MSG)

    def delete_all_slices_except(self, keep_slice_ids: set[str]) -> list[str]:
        raise GenericError(_MSG)

"""tpudev: the TPU host device layer (L0) — the NVML-binding analogue.

The reference's only native boundary is `pkg/gpu/nvml/` (cgo NVML client
behind `//go:build nvml`, pure-Go stub otherwise). Here the same dual:

- `NativeTpudevClient` (`native.py`): ctypes binding over the C++
  `libtpudev` library (`native/tpudev/`), which enumerates `/dev/accel*`
  chips, reads ICI topology, and materializes sub-slice visibility sets for
  the device plugin on a real TPU-VM host.
- `StubTpudevClient` (`stub.py`): the default, hardware-free build.
- `FakeTpudevClient` (`fake.py`): in-memory host for tests/simulation.
"""

from walkai_nos_tpu.tpudev.client import (  # noqa: F401
    ChipInfo,
    HostTopology,
    SliceInfo,
    TpudevClient,
)
from walkai_nos_tpu.tpudev.fake import FakeTpudevClient  # noqa: F401
from walkai_nos_tpu.tpudev.stub import StubTpudevClient  # noqa: F401

"""In-memory fake TPU host (the mockery-mock analogue, but stateful).

Tracks chip occupancy so overlapping creates fail the way the real device
layer would; used by unit tests, the simulation harness, and the fake
device plugin.
"""

from __future__ import annotations

import threading

from walkai_nos_tpu.tpu import topology as topo
from walkai_nos_tpu.tpu.errors import GenericError, NotFoundError
from walkai_nos_tpu.tpu.tiling import grid as gridlib
from walkai_nos_tpu.tpudev.client import (
    ChipInfo,
    HostTopology,
    SliceInfo,
    TpudevClient,
)
from walkai_nos_tpu.tpudev.env import make_slice_env


class FakeTpudevClient(TpudevClient):
    def __init__(self, mesh: topo.Shape = (2, 4), mesh_index: int = 0) -> None:
        self._mesh = mesh
        self._mesh_index = mesh_index
        self._lock = threading.RLock()
        coords = gridlib.all_coords(mesh)
        self._chips = tuple(
            ChipInfo(chip_id=i, device_path=f"/dev/accel{i}", coords=c)
            for i, c in enumerate(coords)
        )
        self._coord_to_chip = {c.coords: c.chip_id for c in self._chips}
        self._slices: dict[str, SliceInfo] = {}

    # ------------------------------------------------------------- interface

    def get_topology(self) -> HostTopology:
        return HostTopology(
            mesh=self._mesh, chips=self._chips, mesh_index=self._mesh_index
        )

    def list_slices(self) -> list[SliceInfo]:
        with self._lock:
            return sorted(self._slices.values(), key=lambda s: s.slice_id)

    def get_slice_mesh_index(self, slice_id: str) -> int:
        with self._lock:
            if slice_id not in self._slices:
                raise NotFoundError(f"slice {slice_id} not found")
            return self._slices[slice_id].mesh_index

    def create_slices(self, placements: list) -> list[SliceInfo]:
        created: list[SliceInfo] = []
        errors: list[str] = []
        with self._lock:
            occupied: set[int] = set()
            for s in self._slices.values():
                occupied.update(s.chip_ids)
            for p in placements:
                # Mirror the native layer's placement-grammar validation
                # (`parse_placement` in tpudev.cc): the profile must be a
                # well-formed positive mesh shape and the orientation a
                # permutation of its dims. Without this the fake accepts
                # placements real hardware rejects.
                try:
                    profile_dims = sorted(topo.parse_shape(p.profile))
                except ValueError:
                    errors.append(f"{p.slice_id()}: malformed profile")
                    continue
                profile_chips = topo.shape_chip_count(tuple(profile_dims))
                if profile_chips > len(self._chips):
                    # Pool share: this host's slice of a multi-host pool
                    # profile — must cover the entire host mesh (mirrors
                    # the native layer's pool-share rule, tpudev.cc).
                    if (
                        tuple(p.orientation) != self._mesh
                        or any(o != 0 for o in p.offset)
                    ):
                        errors.append(
                            f"{p.slice_id()}: pool share must cover the "
                            f"whole host mesh {self._mesh}"
                        )
                        continue
                elif sorted(p.orientation) != profile_dims:
                    errors.append(
                        f"{p.slice_id()}: orientation {p.orientation} is "
                        f"not a permutation of profile {p.profile}"
                    )
                    continue
                try:
                    chip_ids = tuple(
                        self._coord_to_chip[c] for c in p.cells()
                    )
                except KeyError:
                    errors.append(f"{p.slice_id()}: cell outside host mesh")
                    continue
                if p.slice_id() in self._slices:
                    errors.append(f"{p.slice_id()}: already exists")
                    continue
                if occupied.intersection(chip_ids):
                    errors.append(f"{p.slice_id()}: chips already in a slice")
                    continue
                info = SliceInfo(
                    slice_id=p.slice_id(),
                    profile=p.profile,
                    mesh_index=self._mesh_index,
                    chip_ids=chip_ids,
                    env=make_slice_env(p, chip_ids),
                )
                self._slices[info.slice_id] = info
                occupied.update(chip_ids)
                created.append(info)
        if not created and errors:
            raise GenericError("; ".join(errors))
        return created

    def delete_slice(self, slice_id: str) -> None:
        with self._lock:
            if slice_id not in self._slices:
                raise NotFoundError(f"slice {slice_id} not found")
            del self._slices[slice_id]

    def delete_all_slices_except(self, keep_slice_ids: set[str]) -> list[str]:
        with self._lock:
            doomed = [s for s in self._slices if s not in keep_slice_ids]
            for s in doomed:
                del self._slices[s]
            return sorted(doomed)

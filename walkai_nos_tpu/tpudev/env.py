"""Slice runtime-env synthesis — the enforcement contract of a TPU slice.

Where the reference's MIG layer gets hardware-level isolation from the
driver (`pkg/gpu/nvml/client.go` creates GPU/compute instances), a TPU
"slice" on a host is enforced by *visibility*: the device plugin injects
this env into the allocated container so the JAX/libtpu process only
initializes its sub-mesh. This module is that contract, shared by the
real native client (`tpudev/native.py`) and the in-memory fake
(`tpudev/fake.py`); see also `native/tpudev/tpudev.h`.
"""

from __future__ import annotations


def make_slice_env(placement, chip_ids: tuple[int, ...]) -> dict:
    """TPU runtime env for a slice: what the device plugin injects so a JAX
    process only initializes its sub-slice."""
    return {
        "TPU_VISIBLE_CHIPS": ",".join(str(c) for c in chip_ids),
        "TPU_PROCESS_BOUNDS": "1,1,1",
        "TPU_CHIPS_PER_PROCESS_BOUNDS": ",".join(
            str(d) for d in (tuple(placement.orientation) + (1, 1, 1))[:3]
        ),
        "TPU_SLICE_ID": placement.slice_id(),
    }

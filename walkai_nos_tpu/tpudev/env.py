"""Slice runtime-env synthesis — the enforcement contract of a TPU slice.

Where the reference's MIG layer gets hardware-level isolation from the
driver (`pkg/gpu/nvml/client.go` creates GPU/compute instances), a TPU
"slice" on a host is enforced by *visibility*: the device plugin injects
this env into the allocated container so the JAX/libtpu process only
initializes its sub-mesh. This module is that contract, shared by the
real native client (`tpudev/native.py`) and the in-memory fake
(`tpudev/fake.py`); see also `native/tpudev/tpudev.h`.
"""

from __future__ import annotations


from typing import Sequence


def make_slice_env(placement, chip_ids: tuple[int, ...]) -> dict:
    """TPU runtime env for a slice: what the device plugin injects so a JAX
    process only initializes its sub-slice."""
    return {
        "TPU_VISIBLE_CHIPS": ",".join(str(c) for c in chip_ids),
        "TPU_PROCESS_BOUNDS": "1,1,1",
        "TPU_CHIPS_PER_PROCESS_BOUNDS": ",".join(
            str(d) for d in (tuple(placement.orientation) + (1, 1, 1))[:3]
        ),
        "TPU_SLICE_ID": placement.slice_id(),
    }


def make_pool_worker_env(
    worker_id: int, worker_hostnames: Sequence[str], port: int = 8476
) -> dict:
    """Multi-host coordinates for a POOL share — the other half of the
    slice contract. A pool share's visibility env (`make_slice_env`)
    covers this host's chips; the gang's processes additionally need to
    find each other, and these are exactly the fields
    `parallel/multihost.resolve_distributed_config` consumes (the same
    env GKE injects on native podslices): worker id = this host's
    `gke-tpu-worker-id` label, hostnames = the pool members in worker
    order, coordinator = worker 0.
    """
    hosts = [h for h in worker_hostnames if h]
    if not hosts:
        raise ValueError("worker_hostnames must be non-empty")
    if not 0 <= worker_id < len(hosts):
        raise ValueError(
            f"worker_id {worker_id} out of range for {len(hosts)} hosts"
        )
    return {
        "TPU_WORKER_ID": str(worker_id),
        "TPU_WORKER_HOSTNAMES": ",".join(hosts),
        "MEGASCALE_COORDINATOR_ADDRESS": f"{hosts[0]}:{port}",
    }

"""TpudevClient interface: the device-control boundary.

Analogue of `nvml.Client` (`pkg/gpu/nvml/interface.go:23-35`) with TPU
semantics: instead of MIG GPU-instance/compute-instance create/destroy, a
"slice" on a TPU-VM host is a *materialized visibility set* — a named group
of chips plus the TPU runtime environment (TPU_VISIBLE_CHIPS /
TPU_PROCESS_BOUNDS / TPU_CHIPS_PER_PROCESS_BOUNDS) that the walkai device
plugin advertises as one `walkai.io/tpu-<shape>` device and injects into
the pod that is allocated the slice.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field

from walkai_nos_tpu.tpu.topology import Shape


@dataclass(frozen=True)
class ChipInfo:
    """One TPU chip on the host."""

    chip_id: int  # host-local ordinal (stable across reboots)
    device_path: str  # e.g. "/dev/accel0"
    coords: tuple[int, ...]  # position in the host ICI mesh


@dataclass(frozen=True)
class HostTopology:
    mesh: Shape  # host ICI mesh shape, e.g. (2, 4)
    chips: tuple[ChipInfo, ...]
    mesh_index: int = 0  # the GpuIndex analogue (one mesh per host)

    @property
    def chip_count(self) -> int:
        return len(self.chips)


@dataclass(frozen=True)
class SliceInfo:
    """A materialized sub-slice."""

    slice_id: str  # e.g. "2x2@0-0" (packing.Placement.slice_id())
    profile: str  # canonical shape, e.g. "2x2"
    mesh_index: int
    chip_ids: tuple[int, ...]  # chips in the visibility set
    env: dict[str, str] = field(default_factory=dict)  # TPU runtime env
    # injected into allocated pods

    @property
    def resource_name(self) -> str:
        from walkai_nos_tpu.api import constants
        from walkai_nos_tpu.tpu.sharing.profile import SharedProfile

        # Chip-count shares ("2c") advertise under the shared prefix;
        # mesh shapes ("2x2") under the slice prefix. The shared grammar
        # has exactly one authority: SharedProfile.
        try:
            SharedProfile.parse(self.profile)
        except ValueError:
            return constants.RESOURCE_TPU_SLICE_PREFIX + self.profile
        return constants.RESOURCE_TPU_SHARED_PREFIX + self.profile


class TpudevClient(abc.ABC):
    """Device-control boundary (reference: `nvml/interface.go:23-35`)."""

    @abc.abstractmethod
    def get_topology(self) -> HostTopology:
        """Enumerate chips + ICI mesh (the GetMigEnabledGPUs analogue: a
        host with zero chips is not TPU-partitionable)."""

    @abc.abstractmethod
    def list_slices(self) -> list[SliceInfo]:
        """All currently materialized slices."""

    @abc.abstractmethod
    def get_slice_mesh_index(self, slice_id: str) -> int:
        """Mesh index owning a slice (`GetMigDeviceGpuIndex` analogue);
        raises NotFoundError for unknown slices."""

    @abc.abstractmethod
    def create_slices(self, placements: list) -> list[SliceInfo]:
        """Materialize slices for `packing.Placement`s. All-or-nothing per
        call is NOT guaranteed: returns the successfully created slices and
        raises only if none could be created — mirroring the partial-failure
        tolerance of `mig.Client.CreateMigDevices` (`client.go:50-74`)."""

    @abc.abstractmethod
    def delete_slice(self, slice_id: str) -> None:
        """Tear down one slice (`DeleteMigDevice` analogue); raises
        NotFoundError if absent."""

    @abc.abstractmethod
    def delete_all_slices_except(self, keep_slice_ids: set[str]) -> list[str]:
        """Startup cleanup of orphans (`DeleteAllMigDevicesExcept`,
        `nvml/client.go:369-456`). Returns deleted slice IDs."""

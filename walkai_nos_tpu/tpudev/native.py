"""ctypes binding to the native tpudev library (`native/tpudev/`).

The analogue of the reference's cgo NVML client (`pkg/gpu/nvml/client.go`,
`//go:build nvml`): the real device layer, loaded at runtime, with the
pure-Python stub (`walkai_nos_tpu/tpudev/stub.py`) as the default when the
shared library isn't present — mirroring the build-tag/stub dual
(`client_stub.go:24`).

Library resolution order: $WALKAI_TPUDEV_LIB, then the in-repo build
(`native/tpudev/build/libtpudev.so`), then the system loader.
"""

from __future__ import annotations

import ctypes
import json
import os
from pathlib import Path

from walkai_nos_tpu.tpu.errors import GenericError, NotFoundError
from walkai_nos_tpu.tpudev.client import (
    ChipInfo,
    HostTopology,
    SliceInfo,
    TpudevClient,
)

# Must match TPUDEV_ABI_VERSION in native/tpudev/tpudev.h.
EXPECTED_ABI_VERSION = 1


class AbiMismatchError(GenericError):
    """The loaded libtpudev.so speaks a different ABI than this
    wrapper. Deliberately NOT absorbed by load_client's stub fallback:
    a stale library after a partial deploy must stop the agent, not
    silently degrade it to the noop stub."""

_OK = 0
_ERR = 1
_NOTFOUND = 2
_CONFLICT = 3
_ERANGE = 4
_EINVAL = 5

_BUF_SIZE = 1 << 20

_REPO_BUILD = (
    Path(__file__).resolve().parents[2] / "native" / "tpudev" / "build"
    / "libtpudev.so"
)


def find_library() -> str | None:
    env = os.environ.get("WALKAI_TPUDEV_LIB")
    if env:
        return env if os.path.exists(env) else None
    if _REPO_BUILD.exists():
        return str(_REPO_BUILD)
    # System loader last: a bare soname lets ctypes consult the usual
    # search path (ld.so.conf / LD_LIBRARY_PATH).
    import ctypes.util

    return ctypes.util.find_library("tpudev")


class NativeTpudevClient(TpudevClient):
    """TpudevClient over libtpudev.so."""

    def __init__(self, lib_path: str | None = None) -> None:
        path = lib_path or find_library()
        if path is None:
            raise GenericError(
                "libtpudev.so not found (set WALKAI_TPUDEV_LIB or run "
                "`make -C native/tpudev`)"
            )
        self._lib = ctypes.CDLL(path)
        self._check_abi(path)
        self._lib.tpudev_last_error.restype = ctypes.c_char_p
        self._lib.tpudev_get_topology.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
        ]
        self._lib.tpudev_list_slices.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
        ]
        self._lib.tpudev_create_slice.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
        ]
        self._lib.tpudev_delete_slice.argtypes = [ctypes.c_char_p]
        self._check(self._lib.tpudev_init(), "tpudev_init")

    def _check_abi(self, path: str) -> None:
        """Refuse a mismatched .so at load: a stale library after a
        partial deploy must fail loudly, not corrupt slice records."""
        try:
            version = int(self._lib.tpudev_abi_version())
        except AttributeError:
            version = 0  # predates the handshake entirely
        if version != EXPECTED_ABI_VERSION:
            raise AbiMismatchError(
                f"libtpudev ABI mismatch at {path}: library reports "
                f"{version}, wrapper expects {EXPECTED_ABI_VERSION} — "
                "rebuild with `make -C native/tpudev`"
            )

    # ----------------------------------------------------------------- errors

    def _check(self, status: int, op: str) -> None:
        if status == _OK:
            return
        msg = (self._lib.tpudev_last_error() or b"").decode()
        if status == _NOTFOUND:
            raise NotFoundError(f"{op}: {msg}")
        raise GenericError(f"{op}: {msg or f'status {status}'}")

    def _call_json(self, fn, *args):
        buf = ctypes.create_string_buffer(_BUF_SIZE)
        self._check(fn(*args, buf, _BUF_SIZE), fn.__name__)
        return json.loads(buf.value.decode())

    # -------------------------------------------------------------- interface

    def get_topology(self) -> HostTopology:
        data = self._call_json(self._lib.tpudev_get_topology)
        return HostTopology(
            mesh=tuple(data["mesh"]),
            mesh_index=data["mesh_index"],
            chips=tuple(
                ChipInfo(
                    chip_id=c["chip_id"],
                    device_path=c["device_path"],
                    coords=tuple(c["coords"]),
                )
                for c in data["chips"]
            ),
        )

    def _slice_from_json(self, s: dict) -> SliceInfo:
        from walkai_nos_tpu.tpudev.env import make_slice_env
        from walkai_nos_tpu.tpu.tiling.packing import Placement

        placement = Placement(
            profile=s["profile"],
            offset=tuple(s["offset"]),
            orientation=tuple(s["orientation"]),
        )
        chip_ids = tuple(s["chip_ids"])
        return SliceInfo(
            slice_id=s["slice_id"],
            profile=s["profile"],
            mesh_index=s["mesh_index"],
            chip_ids=chip_ids,
            env=make_slice_env(placement, chip_ids),
        )

    def list_slices(self) -> list[SliceInfo]:
        return [
            self._slice_from_json(s)
            for s in self._call_json(self._lib.tpudev_list_slices)
        ]

    def get_slice_mesh_index(self, slice_id: str) -> int:
        for s in self.list_slices():
            if s.slice_id == slice_id:
                return s.mesh_index
        raise NotFoundError(f"slice {slice_id} not found")

    def create_slices(self, placements: list) -> list[SliceInfo]:
        created: list[SliceInfo] = []
        errors: list[str] = []
        for p in placements:
            text = (
                f"{p.profile}@"
                + "-".join(str(c) for c in p.offset)
                + ":"
                + "x".join(str(d) for d in p.orientation)
            )
            try:
                data = self._call_json(
                    self._lib.tpudev_create_slice, text.encode()
                )
            except GenericError as e:
                errors.append(str(e))
                continue
            created.append(self._slice_from_json(data))
        if not created and errors:
            raise GenericError("; ".join(errors))
        return created

    def delete_slice(self, slice_id: str) -> None:
        self._check(
            self._lib.tpudev_delete_slice(slice_id.encode()),
            "tpudev_delete_slice",
        )

    def delete_all_slices_except(self, keep_slice_ids: set[str]) -> list[str]:
        deleted = []
        for s in self.list_slices():
            if s.slice_id not in keep_slice_ids:
                self.delete_slice(s.slice_id)
                deleted.append(s.slice_id)
        return sorted(deleted)


def load_client() -> TpudevClient:
    """Native client when the library is available, else the noop stub —
    the runtime equivalent of the reference's nvml build-tag dual.
    A present-but-unloadable library (wrong arch -> OSError, missing
    symbol -> AttributeError) degrades the same way a missing one does,
    with the reason logged."""
    try:
        return NativeTpudevClient()
    except AbiMismatchError:
        raise  # fail loudly: the library exists but is the wrong build
    except (GenericError, OSError, AttributeError) as e:
        import logging

        logging.getLogger(__name__).warning(
            "tpudev native library unavailable (%s); using the noop stub",
            e,
        )
        from walkai_nos_tpu.tpudev.stub import StubTpudevClient

        return StubTpudevClient()

"""Scheduling-latency benchmark: pod-create -> bind through the full
control plane.

Measures the second north-star metric (BASELINE.md): p50 time-to-scheduled
for pending slice pods, driven through the REAL controllers — node init,
pending-pod detection, first-fit retiling, agent actuate + report, device
plugin advertising, scheduler bind — over the sim harness's
envtest-analogue fake API server (the reference's only latency envelope is
operational defaults, SURVEY.md §6).

The workload mixes profiles (1x1 / 1x2 / 2x2) so most pods require at
least one retile of a node that initialized to the fewest-slices tiling,
and fills ~85% of cluster chips so the packer works under fragmentation
pressure without requiring a perfect packing.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from walkai_nos_tpu.kube import objects
from walkai_nos_tpu.sim.harness import SimCluster
from walkai_nos_tpu.tpu.annotations import parse_node_annotations
from walkai_nos_tpu.utils.stats import percentile


@dataclass
class SchedulingBenchResult:
    scheduled: int
    unscheduled: int
    p50_s: float
    p90_s: float
    mean_s: float
    max_s: float
    # Sharing path (chip-count shares on sharing-labeled hosts), measured
    # separately so the tiling numbers stay comparable across rounds.
    share_scheduled: int = 0
    share_unscheduled: int = 0
    share_p50_s: float = 0.0
    share_p90_s: float = 0.0


def _workload(n_nodes: int) -> list[tuple[str, str]]:
    """Interleaved (pod-name, profile) plan at ~85% chip fill.

    Ratios per 10 nodes (80 chips): 36x 1x1 + 6x 1x2 + 5x 2x2 = 68 chips.
    """
    total = {k: v * n_nodes // 10 for k, v in
             {"1x1": 36, "1x2": 6, "2x2": 5}.items()}
    # Largest profiles first (first-fit-decreasing): every node still gets
    # retiled at least once (they init to a single 2x4), but big slices
    # claim contiguous regions before 1x1s fragment the meshes — the same
    # ordering discipline an operator would use, since neither the
    # reference nor this framework migrates running pods to defragment.
    order = (
        ["2x2"] * total["2x2"] + ["1x2"] * total["1x2"] + ["1x1"] * total["1x1"]
    )
    return [(f"bench-{i:03d}", p) for i, p in enumerate(order)]


def _percentile(sorted_vals: list[float], q: float) -> float:
    """The SHARED nearest-rank percentile (`utils/stats.percentile`),
    with this module's legacy call shape (fractional q, 0.0 on empty
    — the result fields are unconditionally rounded floats). Was a
    third private floor-rank implementation; `sim/trafficbench.py`
    uses the shared helper directly."""
    p = percentile(sorted_vals, q * 100)
    return 0.0 if p is None else p


def _drive_pods(
    sim: SimCluster,
    plan: list[tuple[str, str]],
    create,
    stagger_s: float,
    timeout_s: float,
) -> list[float]:
    """Create pods per `plan` (staggered), poll until bound or timeout;
    returns sorted create->bind latencies (unbound pods are absent)."""
    created: dict[str, float] = {}
    bound: dict[str, float] = {}
    for name, profile in plan:
        create(name, profile)
        created[name] = time.monotonic()
        time.sleep(stagger_s)
    stop_at = time.monotonic() + timeout_s
    pending = set(created)
    while pending and time.monotonic() < stop_at:
        now = time.monotonic()
        for pod in sim.kube.list("Pod", namespace="default"):
            name = objects.name(pod)
            if name in pending and objects.pod_is_scheduled(pod):
                bound[name] = now
                pending.discard(name)
        time.sleep(0.002)
    return sorted(bound[n] - created[n] for n in bound)


def run_scheduling_benchmark(
    n_nodes: int = 10,
    report_interval: float = 0.02,
    stagger_s: float = 0.01,
    timeout_s: float = 90.0,
) -> SchedulingBenchResult:
    plan = _workload(n_nodes)
    sim = SimCluster(report_interval=report_interval)
    for i in range(n_nodes):
        sim.add_node(f"host-{i}", mesh=(2, 4))
    with sim:
        # Let node init + first status report settle so we measure pod
        # scheduling, not cluster bring-up.
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            ready = 0
            for i in range(n_nodes):
                node = sim.kube.get("Node", f"host-{i}")
                status, _ = parse_node_annotations(objects.annotations(node))
                ready += bool(status)
            if ready == n_nodes:
                break
            time.sleep(report_interval)

        lat = _drive_pods(
            sim, plan, sim.create_slice_pod, stagger_s, timeout_s
        )

    share_plan_len, share_lat = run_sharing_benchmark(
        n_nodes=max(1, n_nodes // 5),
        report_interval=report_interval,
        stagger_s=stagger_s,
        timeout_s=timeout_s,
    )
    return SchedulingBenchResult(
        scheduled=len(lat),
        unscheduled=len(plan) - len(lat),
        p50_s=_percentile(lat, 0.50),
        p90_s=_percentile(lat, 0.90),
        mean_s=sum(lat) / len(lat) if lat else 0.0,
        max_s=lat[-1] if lat else 0.0,
        share_scheduled=len(share_lat),
        share_unscheduled=share_plan_len - len(share_lat),
        share_p50_s=_percentile(share_lat, 0.50),
        share_p90_s=_percentile(share_lat, 0.90),
    )


def run_sharing_benchmark(
    n_nodes: int = 2,
    report_interval: float = 0.02,
    stagger_s: float = 0.01,
    timeout_s: float = 60.0,
) -> tuple[int, list[float]]:
    """(planned count, sorted bind latencies) for chip-count share pods
    on sharing-labeled hosts — plan -> ShareActuator -> share device
    plugins -> bind, the dynamic-MPS analogue."""
    sim = SimCluster(report_interval=report_interval)
    for i in range(n_nodes):
        sim.add_sharing_node(f"share-host-{i}", mesh=(2, 4))
    # 3x 2c + 2x 1c per 8-chip host = 8 chips, full fill.
    plan = []
    for i in range(n_nodes):
        plan += [(f"share-{i}-{j}", "2c") for j in range(3)]
        plan += [(f"share-{i}-{j + 3}", "1c") for j in range(2)]
    with sim:
        # Same settle discipline as the tiling phase: wait for every
        # node's first status report so latencies measure scheduling,
        # not cluster bring-up.
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            ready = 0
            for i in range(n_nodes):
                node = sim.kube.get("Node", f"share-host-{i}")
                status, _ = parse_node_annotations(objects.annotations(node))
                ready += bool(status)
            if ready == n_nodes:
                break
            time.sleep(report_interval)
        lat = _drive_pods(
            sim, plan, sim.create_shared_pod, stagger_s, timeout_s
        )
    return len(plan), lat

from walkai_nos_tpu.sim.harness import SimCluster, SimNode  # noqa: F401

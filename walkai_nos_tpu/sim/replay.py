"""Offline re-execution of capture logs + first-divergence triage.

The read side of the capture plane (`obs/capture.py`): load a capture,
rebuild the serving engine from its config fingerprint (or from the
fingerprint plus explicit knob overrides — e.g. replay a bf16 capture
under `kv_dtype=int8-sim`, or a tp=1 capture at `tp_devices=2`),
re-submit the recorded requests with their original knobs and
EFFECTIVE seeds, and verify every completion digest. Because serving
output is a pure function of (weights, prompt, knobs, seed) —
independent of batch composition, chunking, spec rounds, loop folding,
TP sharding, and quantization-sim — a faithful replay is
token-identical however the replayed batch happens to compose, and
any determinism-preserving override (loop depth, prefix cache on/off,
speculative on/off with ANY draft, tp degree, int8-sim) must verify
clean too. A divergence therefore always means something REAL: changed
weights, a config axis that moves the function, or a violated engine
invariant.

On mismatch, `triage_divergence` makes the failure actionable in one
pass: isolate the FIRST divergent request (arrival order), re-run it
SOLO on a fresh engine to classify the axis —

- solo output == captured output  -> **batch_dependent**: the request
  alone still reproduces the capture, so the divergence appears only
  under batch composition. That is a violated engine invariant (the
  exactness property every parity test pins) — file it as an engine
  bug, not a config question.
- solo output != captured output  -> **config_dependent**: the
  rebuilt (weights, config) pair computes a different function — the
  override (or a weights-digest mismatch) moved the output.

— then report the first divergent token index and dump a
flight-recorder-format bundle (`obs/anomaly.FlightRecorder`, the
PR-14 incident format): both configs' fingerprints, the offending
record, the divergence coordinates, and the replay engine's
debug_state. "Replay the incident, bisect the axis" is then ONE
command: `python -m walkai_nos_tpu.cmd.replay <capture>`.

Timing: `timing="asap"` re-submits in arrival order as fast as the
engine admits (digest verification — the default); `timing="original"`
re-paces submissions to the recorded arrival offsets (scaled by
`speed`) so latency regressions can be reproduced under the original
load shape, not just the original inputs.
"""

from __future__ import annotations

import glob
import json
import os
import time
from dataclasses import dataclass, field

__all__ = [
    "Capture",
    "CaptureRecord",
    "ReplayReport",
    "build_config",
    "build_engine",
    "classify_config_delta",
    "first_divergence",
    "load_capture",
    "replay_capture",
    "triage_divergence",
]

# ContinuousBatcher constructor knobs a fingerprint's `engine` section
# records (everything else in an override targets an LMConfig field).
ENGINE_KNOBS = (
    "slots", "cache_len", "prompt_bucket", "chunk_steps",
    "loop_steps", "paged", "pool_blocks", "prefill_chunk",
    "prefill_lanes", "prefix_cache", "spec", "spec_k",
    "spec_min_accept", "spec_warmup_rounds", "spec_ema_alpha",
    "sp_prefill", "sp_min_tokens", "sp_span",
)

# LMConfig fields whose change preserves token VALUES (the purity
# invariant the replay plane pins): tp degree is proven
# token-identical to tp=1; kv_dtype/w_dtype count separately because
# only SOME transitions preserve tokens (see classify_config_delta).
TOKEN_PRESERVING_CFG_FIELDS = ("tp_devices",)

# Dtype values whose pairwise transitions keep the serving function:
# "int8-sim" runs identity quantization with unit scales, so it is
# token-identical to "model" by construction; real "int8" rounds.
_TOKEN_PRESERVING_DTYPES = frozenset({"model", "int8-sim"})


def classify_config_delta(fp_a: dict, fp_b: dict) -> dict:
    """Classify the config delta between two engine fingerprints —
    the canary plane's up-front gate decision (`obs/canary.py`): is
    the candidate config expected to produce IDENTICAL token streams
    (digest-exact gate armed) or does a delta field move the serving
    function (latency-only comparison)?

    Compares the fingerprints' `cfg` and `engine` sections
    field-by-field — NOT the weights digest: the digest gate exists
    precisely to catch a weights change the config delta cannot
    explain (same knobs, different checkpoint -> gate armed ->
    divergence -> reject). A delta field is token-preserving when it
    is an engine knob (every ENGINE_KNOBS axis is a
    determinism-preserving replay override), a known-safe LMConfig
    field (`tp_devices`), or a kv_dtype/w_dtype transition within
    {"model", "int8-sim"}; anything else — model dims, vocab, real
    int8 — declares the configs different functions.

    Returns `{"delta": [{"section", "field", "a", "b"}, ...],
    "token_preserving": bool, "moving_fields": [...]}`; an empty
    delta (identical configs) is trivially token-preserving."""
    delta: list[dict] = []
    moving: list[str] = []
    for section in ("cfg", "engine"):
        a = dict((fp_a or {}).get(section) or {})
        b = dict((fp_b or {}).get(section) or {})
        for field_name in sorted(set(a) | set(b)):
            va, vb = a.get(field_name), b.get(field_name)
            if va == vb:
                continue
            delta.append({
                "section": section, "field": field_name,
                "a": va, "b": vb,
            })
            if section == "engine":
                if field_name in ENGINE_KNOBS:
                    continue
            elif field_name in TOKEN_PRESERVING_CFG_FIELDS:
                continue
            elif field_name in ("kv_dtype", "w_dtype") and {
                va, vb
            } <= _TOKEN_PRESERVING_DTYPES:
                continue
            moving.append(f"{section}.{field_name}")
    # The multi-LoRA plane (fp["lora"], adapter digests + recipe): a
    # differing adapter set computes a different function for every
    # request routed at the changed ids — conservatively
    # function-moving, like a weights delta.
    lora_a = (fp_a or {}).get("lora")
    lora_b = (fp_b or {}).get("lora")
    if lora_a != lora_b:
        delta.append({
            "section": "lora", "field": "adapters",
            "a": lora_a, "b": lora_b,
        })
        moving.append("lora.adapters")
    return {
        "delta": delta,
        "token_preserving": not moving,
        "moving_fields": moving,
    }


@dataclass
class CaptureRecord:
    """One captured request: the submit-side inputs (always present)
    merged with the done-side outputs (None until the request
    completed inside the retained capture window)."""

    rid: int
    prompt: list
    max_new_tokens: int = 1
    eos_id: int | None = None
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int | None = None
    adapter: int = 0  # multi-LoRA adapter id (0 = base model)
    arrival_s: float = 0.0
    trace_id: str | None = None
    replica: str | None = None
    tokens: list | None = None
    digest: str | None = None
    ttft_s: float | None = None
    wall_s: float | None = None
    truncated: bool = False
    reason: str | None = None
    error: str | None = None  # fleet captures: failed replica request
    # Fleet captures: True on the shadow copy a canary-armed router
    # mirrored to its candidate replica. Mirrored rows never represent
    # user traffic — load_capture drops them by default so a replay
    # of a canary-armed window does not double-count every sampled
    # request.
    mirrored: bool = False


@dataclass
class Capture:
    fingerprint: dict
    records: list[CaptureRecord]  # arrival order
    skipped: int  # malformed lines + orphan done records
    files: list[str]
    runs: int = 1  # engine runs found in the file set
    run: int = 0  # which run this Capture holds (0-based)
    mirrored_skipped: int = 0  # canary shadow rows dropped at load

    @property
    def fingerprint_id(self) -> str | None:
        return (self.fingerprint or {}).get("id")


def load_capture(
    path: str,
    *,
    run: int | None = None,
    include_mirrored: bool = False,
) -> Capture:
    """Parse a capture file, or a directory of rotated capture files
    (oldest first — each file is self-contained behind its own
    header). Malformed lines are skipped and counted, never fatal: a
    capture that survived a crash mid-write must still replay. A done
    record whose submit rotated away is an orphan (counted skipped);
    a submit with no done replays but cannot verify.

    A directory may span several ENGINE RUNS (a restarted server
    keeps appending to the same WALKAI_CAPTURE_DIR, continuing the
    file sequence): request ids restart at 0 per run, so runs must
    never be merged — a run-1 done pairing with a run-2 submit would
    produce false verdicts, and rid collisions would silently drop
    records. Runs are split on the header's `created_unix_s` (one
    `attach()` writes byte-identical headers into every file it
    rotates through; a restart stamps a new one). `run` selects
    which run to load (0-based, negative from the end); default the
    LATEST — the incident-relevant one. `Capture.runs` says how many
    were found so callers can surface the choice.

    A canary-armed fleet's capture carries each sampled request TWICE
    — the primary row serving the user plus a `mirrored: true` shadow
    row — so mirrored records are dropped by default (counted in
    `Capture.mirrored_skipped`); `include_mirrored=True` keeps them
    for shadow-side forensics."""
    if os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, "capture-*.jsonl")))
    else:
        files = [path]
    if not files or not all(os.path.isfile(f) for f in files):
        raise FileNotFoundError(f"no capture files at {path!r}")
    # One bucket per engine run: {header, submits, dones, skipped}.
    run_keys: dict[tuple, int] = {}
    buckets: list[dict] = []
    stray_skipped = 0  # lines before any header / orphan records
    current: dict | None = None
    for fname in files:
        with open(fname) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    # Attribute corruption to the run it sits in —
                    # a crash-corrupted line in run 1 must not read
                    # as run 0 having lost a record.
                    if current is not None:
                        current["skipped"] += 1
                    else:
                        stray_skipped += 1
                    continue
                if not isinstance(obj, dict):
                    if current is not None:
                        current["skipped"] += 1
                    else:
                        stray_skipped += 1
                    continue
                kind = obj.get("kind")
                if kind == "header":
                    fp = obj.get("fingerprint") or {}
                    key = (obj.get("created_unix_s"), fp.get("id"))
                    idx = run_keys.get(key)
                    if idx is None:
                        run_keys[key] = len(buckets)
                        buckets.append({
                            "header": fp, "submits": {},
                            "dones": {}, "skipped": 0,
                        })
                        idx = run_keys[key]
                    current = buckets[idx]
                elif current is None:
                    stray_skipped += 1
                elif kind == "submit" and "rid" in obj:
                    current["submits"][obj["rid"]] = obj
                elif kind == "done" and "rid" in obj:
                    current["dones"][obj["rid"]] = obj
                else:
                    current["skipped"] += 1
    if not buckets:
        raise ValueError(
            f"no capture header found at {path!r} (not a capture, or "
            f"every header line is corrupt)"
        )
    idx = len(buckets) - 1 if run is None else run
    try:
        bucket = buckets[idx]
    except IndexError:
        raise ValueError(
            f"capture at {path!r} holds {len(buckets)} run(s); "
            f"run={run} is out of range"
        ) from None
    idx = idx % len(buckets)  # normalize negative selectors
    submits, dones = bucket["submits"], bucket["dones"]
    # Orphan dones: their submit record was pruned by rotation.
    skipped = bucket["skipped"] + stray_skipped + sum(
        1 for rid in dones if rid not in submits
    )
    known = {f.name for f in CaptureRecord.__dataclass_fields__.values()}
    records = []
    mirrored_skipped = 0
    for rid in sorted(
        submits, key=lambda r: (submits[r].get("arrival_s", 0.0), r)
    ):
        merged = {**submits[rid], **(dones.get(rid) or {})}
        rec = CaptureRecord(**{
            k: v for k, v in merged.items() if k in known
        })
        if rec.mirrored and not include_mirrored:
            mirrored_skipped += 1
            continue
        records.append(rec)
    return Capture(
        bucket["header"], records, skipped, files,
        runs=len(buckets), run=idx,
        mirrored_skipped=mirrored_skipped,
    )


def build_config(fingerprint: dict, overrides: dict | None = None):
    """(LMConfig, engine_kwargs) from a fingerprint, with overrides
    applied — an override key is an engine knob when it names one,
    else an LMConfig field, else an error (a typo'd axis must not
    silently replay the unmodified config and report 'no
    divergence')."""
    import dataclasses

    from walkai_nos_tpu.models.lm import LMConfig

    cfg_fields = dict(fingerprint.get("cfg") or {})
    eng = dict(fingerprint.get("engine") or {})
    if not cfg_fields or not eng:
        raise ValueError(
            "fingerprint has no cfg/engine sections (a fleet-level "
            "router capture? engine captures are the replayable "
            "artifact)"
        )
    valid_cfg = {f.name for f in dataclasses.fields(LMConfig)}
    for key, value in (overrides or {}).items():
        if key in ENGINE_KNOBS:
            eng[key] = value
        elif key in valid_cfg:
            cfg_fields[key] = value
        else:
            raise ValueError(
                f"unknown override {key!r}: not an engine knob "
                f"{ENGINE_KNOBS} or an LMConfig field"
            )
    cfg_fields = {
        k: v for k, v in cfg_fields.items() if k in valid_cfg
    }
    return LMConfig(**cfg_fields), eng


def build_engine(
    fingerprint: dict,
    params,
    *,
    overrides: dict | None = None,
    draft_cfg=None,
    draft_params=None,
    draft_seed: int = 0,
    obs=False,
    capture=None,
    adapters=None,
):
    """Rebuild a ContinuousBatcher from a capture fingerprint (plus
    overrides). `params` is the caller's weight tree — captures store
    a digest, not weights; `cmd/replay.py` re-initializes from a seed
    and warns on digest mismatch. A spec replay with no draft given
    builds an UNTRAINED draft (draft_config + init): speculative
    serving is token-identical to spec-off for ANY draft weights, so
    an untrained draft is a correct replay axis, not an
    approximation.

    A LoRA-armed capture (fingerprint carries a `lora` section) is
    replayed with a rebuilt adapter plane: a synthetic recipe in the
    fingerprint reconstructs the EXACT adapter set from its seed, so
    the replay is digest-exact with zero stored weights; a capture of
    real (recipe-less) adapters needs the caller to pass `adapters`
    (an AdapterSet matching the recorded digests) — rebuilding that
    from a digest alone is as impossible as rebuilding base weights."""
    from walkai_nos_tpu.models.serve import ContinuousBatcher

    cfg, eng = build_config(fingerprint, overrides)
    lora_fp = (fingerprint or {}).get("lora")
    if adapters is None and lora_fp:
        recipe = dict(lora_fp.get("recipe") or {})
        if recipe.pop("kind", None) != "synthetic":
            raise ValueError(
                "capture fingerprint records real LoRA adapters "
                f"(digests {lora_fp.get('digests')}); pass adapters= "
                "with the matching AdapterSet to replay it"
            )
        from walkai_nos_tpu.models.lora import AdapterSet

        adapters = AdapterSet.synthetic(cfg, **recipe)
    kwargs = {
        k: eng[k] for k in ENGINE_KNOBS
        if k in eng and k not in ("spec",)
    }
    if not kwargs.get("paged", True):
        # Dense engines record pool_blocks=0; the constructor derives
        # its own (unused) value.
        kwargs.pop("pool_blocks", None)
    elif not kwargs.get("pool_blocks"):
        kwargs.pop("pool_blocks", None)
    if eng.get("spec"):
        if draft_cfg is None:
            from walkai_nos_tpu.models.lm import draft_config

            draft_cfg = draft_config(cfg)
        if draft_params is None:
            import jax

            from walkai_nos_tpu.models.lm import DecoderLM

            draft_params = DecoderLM(draft_cfg).init_params(
                jax.random.PRNGKey(draft_seed)
            )
        kwargs.update(
            spec=True, draft_cfg=draft_cfg, draft_params=draft_params,
        )
    if adapters is not None:
        kwargs["adapters"] = adapters
    return ContinuousBatcher(
        cfg, params, obs=obs, capture=capture, **kwargs
    )


@dataclass
class ReplayOutcome:
    rid: int
    arrival_s: float
    tokens: list | None  # replayed output (None: submit rejected)
    expected: list | None  # captured output (None: never completed)
    match: bool | None = None  # None: unverifiable (never completed)
    first_divergent_token: int | None = None
    error: str | None = None  # replay-side submit rejection


@dataclass
class ReplayReport:
    fingerprint_id: str | None
    overrides: dict
    outcomes: dict[int, ReplayOutcome] = field(default_factory=dict)
    divergent: list[int] = field(default_factory=list)  # arrival order
    n_requests: int = 0
    n_verified: int = 0
    skipped_records: int = 0
    replay_wall_s: float = 0.0

    @property
    def ok(self) -> bool:
        return not self.divergent

    def summary(self) -> dict:
        return {
            "fingerprint": self.fingerprint_id,
            "overrides": self.overrides,
            "requests": self.n_requests,
            "verified": self.n_verified,
            "divergent": len(self.divergent),
            "first_divergent_rid": (
                self.divergent[0] if self.divergent else None
            ),
            "skipped_records": self.skipped_records,
            "replay_wall_s": round(self.replay_wall_s, 3),
            "ok": self.ok,
        }


def first_divergence(expected: list, got: list) -> int:
    """Index of the first divergent token between two streams (a
    stream that is a strict prefix of the other diverges at the
    shorter length). Shared by replay verification and the canary
    plane's per-request digest diff (`obs/canary.py`)."""
    for i, (a, b) in enumerate(zip(expected, got)):
        if int(a) != int(b):
            return i
    return min(len(expected), len(got))


# Backward-compatible private alias (pre-canary internal name).
_first_divergence = first_divergence


def _submit_record(engine, rec: CaptureRecord) -> int:
    return engine.submit(
        rec.prompt,
        max_new_tokens=rec.max_new_tokens,
        eos_id=rec.eos_id,
        temperature=rec.temperature,
        top_k=rec.top_k,
        top_p=rec.top_p,
        seed=rec.seed,
        adapter=rec.adapter,
    )


def replay_capture(
    capture: Capture,
    params=None,
    *,
    engine=None,
    overrides: dict | None = None,
    timing: str = "asap",
    speed: float = 1.0,
    draft_cfg=None,
    draft_params=None,
    draft_seed: int = 0,
    obs=False,
    adapters=None,
) -> ReplayReport:
    """Re-execute a capture and verify every completion. Pass either
    a prebuilt `engine` or the weight tree `params` (the engine is
    then rebuilt from the capture's fingerprint + `overrides`).
    Returns a ReplayReport; `report.ok` is the zero-divergence
    verdict `cmd/replay.py` (and `make replay-check`) exits on."""
    if timing not in ("asap", "original"):
        raise ValueError(
            f"timing must be 'asap' or 'original'; got {timing!r}"
        )
    if engine is None:
        if params is None:
            raise ValueError("replay_capture needs params or engine")
        engine = build_engine(
            capture.fingerprint, params, overrides=overrides,
            draft_cfg=draft_cfg, draft_params=draft_params,
            draft_seed=draft_seed, obs=obs, adapters=adapters,
        )
    report = ReplayReport(
        fingerprint_id=capture.fingerprint_id,
        overrides=dict(overrides or {}),
        n_requests=len(capture.records),
        skipped_records=capture.skipped,
    )
    t0 = time.monotonic()
    rid_map: dict[int, CaptureRecord] = {}
    rejected: list[tuple[CaptureRecord, str]] = []

    def submit(rec: CaptureRecord) -> None:
        try:
            rid_map[_submit_record(engine, rec)] = rec
        except ValueError as bad:
            # A replay-side rejection (e.g. an override shrank the
            # admissible space) is a divergence too — the original
            # engine served this request.
            rejected.append((rec, str(bad)))

    if timing == "asap":
        for rec in capture.records:
            submit(rec)
        results = engine.run()
    else:
        speed = max(speed, 1e-9)
        results = {}
        pending = list(capture.records)
        while pending or engine.has_work:
            now = time.monotonic() - t0
            while pending and pending[0].arrival_s / speed <= now:
                submit(pending.pop(0))
            if engine.has_work:
                engine.step()
                results.update(engine.drain_done())
            elif pending:
                time.sleep(
                    min(0.01, pending[0].arrival_s / speed - now)
                )
        results.update(engine.drain_done())

    for new_rid, rec in rid_map.items():
        got = results.get(new_rid)
        out = ReplayOutcome(
            rid=rec.rid, arrival_s=rec.arrival_s,
            tokens=list(got) if got is not None else None,
            expected=rec.tokens,
        )
        if rec.tokens is None or got is None:
            out.match = None  # unverifiable: capture never completed
        else:
            expected = list(map(int, rec.tokens))
            replayed = list(map(int, got))
            if rec.truncated:
                # A pool-truncated completion's length is a function
                # of LIVE pool pressure, not of the purity invariant
                # (which covers token VALUES): the replay may cut at
                # a different point or run to budget. Either stream
                # being a prefix of the other is a verified match —
                # only a value divergence inside the common prefix
                # is real.
                n = min(len(expected), len(replayed))
                out.match = expected[:n] == replayed[:n]
            else:
                out.match = expected == replayed
            report.n_verified += 1
            if not out.match:
                out.first_divergent_token = first_divergence(
                    expected, replayed
                )
        report.outcomes[rec.rid] = out
    for rec, err in rejected:
        report.outcomes[rec.rid] = ReplayOutcome(
            rid=rec.rid, arrival_s=rec.arrival_s, tokens=None,
            expected=rec.tokens, match=False, error=err,
            first_divergent_token=0 if rec.tokens else None,
        )
    report.divergent = [
        rec.rid for rec in capture.records
        if report.outcomes.get(rec.rid) is not None
        and report.outcomes[rec.rid].match is False
    ]
    report.replay_wall_s = time.monotonic() - t0
    return report


def triage_divergence(
    capture: Capture,
    report: ReplayReport,
    params,
    *,
    overrides: dict | None = None,
    draft_cfg=None,
    draft_params=None,
    draft_seed: int = 0,
    flight=None,
    flight_dir: str | None = None,
    adapters=None,
) -> dict | None:
    """First-divergence triage: isolate the earliest divergent
    request, re-run it SOLO on a fresh engine (same replay config) to
    classify batch-dependent vs config-dependent, and dump a
    flight-recorder-format bundle (both configs' fingerprints, the
    offending record, the divergence coordinates, the solo engine's
    debug_state). Returns the triage verdict (None when the replay
    was clean)."""
    if report.ok:
        return None
    rid = report.divergent[0]
    rec = next(r for r in capture.records if r.rid == rid)
    outcome = report.outcomes[rid]
    solo_engine = build_engine(
        capture.fingerprint, params, overrides=overrides,
        draft_cfg=draft_cfg, draft_params=draft_params,
        draft_seed=draft_seed, obs=False, adapters=adapters,
    )
    solo_tokens: list | None = None
    solo_error: str | None = None
    try:
        solo_rid = _submit_record(solo_engine, rec)
        solo_tokens = solo_engine.run().get(solo_rid)
    except ValueError as bad:
        solo_error = str(bad)
    captured = list(map(int, rec.tokens or []))
    if solo_tokens is None:
        solo_matches_capture = False
    elif rec.truncated:
        # Same prefix rule as verification: a truncation point is
        # pool pressure, not the serving function.
        n = min(len(captured), len(solo_tokens))
        solo_matches_capture = list(map(int, solo_tokens))[:n] == (
            captured[:n]
        )
    else:
        solo_matches_capture = (
            list(map(int, solo_tokens)) == captured
        )
    classification = (
        # The request ALONE still reproduces the capture: the
        # divergence appears only under batch composition — a
        # violated engine invariant, not a config question.
        "batch_dependent" if solo_matches_capture
        else "config_dependent"
    )
    verdict = {
        "rid": rid,
        "trace_id": rec.trace_id,
        "token_index": outcome.first_divergent_token,
        "expected_token": (
            captured[outcome.first_divergent_token]
            if outcome.first_divergent_token is not None
            and outcome.first_divergent_token < len(captured)
            else None
        ),
        "got_token": (
            outcome.tokens[outcome.first_divergent_token]
            if outcome.tokens is not None
            and outcome.first_divergent_token is not None
            and outcome.first_divergent_token < len(outcome.tokens)
            else None
        ),
        "classification": classification,
        "divergent_requests": len(report.divergent),
        "solo_error": solo_error or outcome.error,
    }
    if flight is None:
        from walkai_nos_tpu.obs.anomaly import FlightRecorder

        # min_interval 0: consecutive triage runs must both land
        # (the anomaly recorder's throttle exists for flap storms,
        # not for an operator re-running a bisect).
        flight = FlightRecorder(flight_dir, min_interval_s=0.0)
    bundle = {
        "verdict": dict(verdict),
        "capture_fingerprint": capture.fingerprint,
        "replay_fingerprint": solo_engine.config_fingerprint(),
        "overrides": dict(overrides or {}),
        "record": {
            "rid": rec.rid, "prompt": rec.prompt,
            "max_new_tokens": rec.max_new_tokens,
            "eos_id": rec.eos_id, "temperature": rec.temperature,
            "top_k": rec.top_k, "top_p": rec.top_p, "seed": rec.seed,
            "adapter": rec.adapter,
            "arrival_s": rec.arrival_s, "trace_id": rec.trace_id,
            "captured_tokens": rec.tokens,
            "captured_digest": rec.digest,
        },
        "replayed_tokens": outcome.tokens,
        "solo_tokens": (
            list(map(int, solo_tokens))
            if solo_tokens is not None else None
        ),
        "debug_state": solo_engine.debug_state(),
    }
    verdict["bundle_path"] = flight.dump("replay_divergence", bundle)
    return verdict

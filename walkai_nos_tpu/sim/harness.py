"""Hardware-free cluster simulation — the kind-cluster/envtest analogue.

Wires the real controllers (partitioner pod/node controllers, tpuagent
reporter/actuator) against the in-memory fakes (kube API, tpudev hosts,
kubelet resource clients) plus two simulated cluster components:

- a *device-plugin simulator*: respawns the walkai device-plugin pod when
  the actuator restarts it (DaemonSet behavior) and re-advertises the
  host's materialized slices as allocatable devices (what the real plugin
  does via the kubelet device-plugin API);
- a *scheduler simulator*: marks pending slice-requesting pods
  Unschedulable (so the partitioner considers them), binds them to a node
  once the wanted devices are allocatable, and marks devices used (what
  kube-scheduler + kubelet do).

This is the reference's §7.3 "minimum end-to-end slice": label a node,
node-init writes the default tiling, the agent materializes + reports, a
pending pod triggers re-tiling, the pod schedules.
"""

from __future__ import annotations

import threading
import uuid

from walkai_nos_tpu.api import constants
from walkai_nos_tpu.controllers.tpuagent import (
    Actuator,
    Reporter,
    SharedState,
)
from walkai_nos_tpu.kube import objects, predicates
from walkai_nos_tpu.kube.client import KubeClient, NotFound
from walkai_nos_tpu.kube.fake import FakeKubeClient
from walkai_nos_tpu.kube.runtime import Controller, Manager, Request, Result
from walkai_nos_tpu.resource.fake import FakeResourceClient
from walkai_nos_tpu.tpu.device import Device, DeviceStatus
from walkai_nos_tpu.tpu.tiling.client import DevicePluginClient, TilingClient
from walkai_nos_tpu.tpu.tiling.profile import get_requested_profiles
from walkai_nos_tpu.tpu.topology import Shape
from walkai_nos_tpu.tpudev.fake import FakeTpudevClient


class SimNode:
    """One simulated TPU host: tpudev + kubelet resources + agent.

    `kind` is "tiling" (slices from the fake tpudev) or "sharing"
    (shares assigned from the node's spec annotations)."""

    def __init__(
        self,
        name: str,
        mesh: Shape = (2, 4),
        accelerator: str = "tpu-v5-lite-podslice",
        kind: str = "tiling",
    ) -> None:
        self.name = name
        self.mesh = mesh
        self.accelerator = accelerator
        self.kind = kind
        self.tpudev = FakeTpudevClient(mesh=mesh)
        self.resources = FakeResourceClient()
        self.shared = SharedState()
        from walkai_nos_tpu.tpu.sharing.assign import ShareAssigner
        from walkai_nos_tpu.tpu.topology import shape_chip_count

        self.share_assigner = ShareAssigner(shape_chip_count(mesh))

    def _inventory(self) -> list:
        if self.kind == "sharing":
            return self.share_assigner.shares()
        return self.tpudev.list_slices()

    def advertise_slices(self) -> None:
        """What the device plugin does on (re)start: advertise every
        materialized slice/share as an allocatable device."""
        used_ids = {
            d.device_id for d in self.resources.get_used_devices()
        }
        self.resources.set_allocatable(
            [
                Device(
                    resource_name=s.resource_name,
                    device_id=s.slice_id,
                    status=DeviceStatus.UNKNOWN,
                    mesh_index=s.mesh_index,
                )
                for s in self._inventory()
            ]
        )
        for dev_id in used_ids:
            self.resources.mark_used(dev_id)


class SimCluster:
    def __init__(
        self,
        report_interval: float = 0.05,
        kube: "KubeClient | None" = None,
    ) -> None:
        # Injectable API-server boundary: FakeKubeClient by default, or a
        # RestKubeClient against a real HTTP server for envtest-grade e2e
        # (tests/test_e2e_apiserver.py).
        self.kube = kube if kube is not None else FakeKubeClient()
        self.nodes: dict[str, SimNode] = {}
        self.manager = Manager()
        self._report_interval = report_interval
        self._partitioner_wired = False
        self._lock = threading.Lock()

    # ------------------------------------------------------------- topology

    def add_node(
        self,
        name: str,
        mesh: Shape = (2, 4),
        accelerator: str = "tpu-v5-lite-podslice",
        topology_label: str | None = None,
    ) -> SimNode:
        sim = SimNode(name, mesh=mesh, accelerator=accelerator)
        self.nodes[name] = sim
        self.kube.create(
            "Node",
            {
                "metadata": {
                    "name": name,
                    "labels": {
                        constants.LABEL_TPU_ACCELERATOR: accelerator,
                        constants.LABEL_TPU_TOPOLOGY: topology_label
                        or "x".join(str(d) for d in mesh),
                        constants.LABEL_TPU_PARTITIONING: "tiling",
                    },
                },
                "status": {"capacity": {}, "allocatable": {}},
            },
        )
        self._create_plugin_pod(name)
        self._wire_agent(sim)
        return sim

    def add_pool(
        self,
        pool_name: str,
        n_hosts: int = 2,
        host_mesh: Shape = (2, 2, 1),
        pool_topology: str = "2x2x2",
        accelerator: str = "tpu-v5p-slice",
    ) -> list[SimNode]:
        """A multi-host pool: N hosts sharing one `gke-tpu-topology`,
        grouped by the nodepool label with stable worker indices — the
        v5p/v4 pod-slice shape the pool-level planner manages
        (`tpu/tiling/pool.py`). Each host runs its own agent over its own
        tpudev, exactly like a single-host node."""
        sims = []
        for i in range(n_hosts):
            node_name = f"{pool_name}-{i}"
            sim = SimNode(node_name, mesh=host_mesh, accelerator=accelerator)
            self.nodes[node_name] = sim
            self.kube.create(
                "Node",
                {
                    "metadata": {
                        "name": node_name,
                        "labels": {
                            constants.LABEL_TPU_ACCELERATOR: accelerator,
                            constants.LABEL_TPU_TOPOLOGY: pool_topology,
                            constants.LABEL_TPU_PARTITIONING: "tiling",
                            constants.LABEL_TPU_NODEPOOL: pool_name,
                            constants.LABEL_TPU_WORKER_ID: str(i),
                        },
                    },
                    "status": {"capacity": {}, "allocatable": {}},
                },
            )
            self._create_plugin_pod(node_name)
            self._wire_agent(sim)
            sims.append(sim)
        return sims

    def add_sharing_node(
        self,
        name: str,
        mesh: Shape = (2, 4),
        accelerator: str = "tpu-v5-lite-podslice",
    ) -> SimNode:
        """A chip-count-sharing host: ShareActuator + sharing Reporter
        instead of the tiling agent pair."""
        sim = SimNode(name, mesh=mesh, accelerator=accelerator, kind="sharing")
        self.nodes[name] = sim
        self.kube.create(
            "Node",
            {
                "metadata": {
                    "name": name,
                    "labels": {
                        constants.LABEL_TPU_ACCELERATOR: accelerator,
                        constants.LABEL_TPU_TOPOLOGY: "x".join(
                            str(d) for d in mesh
                        ),
                        constants.LABEL_TPU_PARTITIONING: "sharing",
                    },
                },
                "status": {"capacity": {}, "allocatable": {}},
            },
        )
        self._create_plugin_pod(name)
        self._wire_sharing_agent(sim)
        return sim

    def _create_plugin_pod(self, node_name: str) -> None:
        self.kube.create(
            "Pod",
            {
                "metadata": {
                    "name": (
                        f"walkai-tpu-device-plugin-{node_name}"
                        f"-{uuid.uuid4().hex[:5]}"
                    ),
                    "namespace": "kube-system",
                    "labels": {
                        constants.DEVICE_PLUGIN_LABEL_KEY:
                            constants.DEVICE_PLUGIN_LABEL_VALUE
                    },
                    "ownerReferences": [
                        {"kind": "DaemonSet",
                         "name": "walkai-tpu-device-plugin"}
                    ],
                },
                "spec": {"nodeName": node_name},
                "status": {"phase": "Running"},
            },
        )

    # ------------------------------------------------------------ controllers

    def _wire_agent(self, sim: SimNode) -> None:
        tiling_client = TilingClient(sim.resources, sim.tpudev)
        plugin_client = DevicePluginClient(
            self.kube, poll_interval=0.01, restart_timeout=5.0
        )
        reporter = Reporter(
            self.kube,
            tiling_client,
            sim.shared,
            sim.name,
            refresh_interval=self._report_interval,
        )
        actuator = Actuator(
            self.kube, tiling_client, plugin_client, sim.shared, sim.name
        )
        self.manager.add(
            Controller(
                f"reporter-{sim.name}",
                self.kube,
                "Node",
                reporter.reconcile,
                predicates=[
                    predicates.matching_name(sim.name),
                    predicates.exclude_delete(),
                ],
            )
        )
        self.manager.add(
            Controller(
                f"actuator-{sim.name}",
                self.kube,
                "Node",
                actuator.reconcile,
                predicates=[
                    predicates.matching_name(sim.name),
                    predicates.exclude_delete(),
                    predicates.annotations_changed(),
                ],
            )
        )

    def _wire_sharing_agent(self, sim: SimNode) -> None:
        from walkai_nos_tpu.controllers.tpuagent.share_actuator import (
            ShareActuator,
        )
        from walkai_nos_tpu.tpu.sharing.client import SharingClient
        from walkai_nos_tpu.tpu.sharing.profile import (
            extract_shared_profile_name,
        )

        class _SimShareManager:
            """set_geometry target: the plugin simulator re-advertises
            the assigner's shares on its next tick."""

            def set_geometry(self, geometry, pinned_ids=None):
                sim.share_assigner.set_geometry(geometry, pinned_ids)

        sharing_client = SharingClient(sim.resources)
        reporter = Reporter(
            self.kube,
            sharing_client,
            sim.shared,
            sim.name,
            refresh_interval=self._report_interval,
            profile_extractor=extract_shared_profile_name,
        )
        actuator = ShareActuator(
            self.kube,
            sim.shared,
            sim.name,
            _SimShareManager(),
            sharing_client=sharing_client,
        )
        self.manager.add(
            Controller(
                f"sharing-reporter-{sim.name}",
                self.kube,
                "Node",
                reporter.reconcile,
                predicates=[
                    predicates.matching_name(sim.name),
                    predicates.exclude_delete(),
                ],
            )
        )
        self.manager.add(
            Controller(
                f"sharing-actuator-{sim.name}",
                self.kube,
                "Node",
                actuator.reconcile,
                predicates=[
                    predicates.matching_name(sim.name),
                    predicates.exclude_delete(),
                    predicates.annotations_changed(),
                ],
            )
        )

    def wire_partitioner(self) -> None:
        if self._partitioner_wired:
            return
        self._partitioner_wired = True
        # The PRODUCTION wiring, verbatim — the sim exists to exercise the
        # same controllers/predicates the tpupartitioner binary runs.
        from walkai_nos_tpu.cmd.tpupartitioner import build_manager
        from walkai_nos_tpu.config import PartitionerConfig

        for controller in build_manager(self.kube, PartitionerConfig()).controllers:
            self.manager.add(controller)
        # simulators. The device-plugin simulator is keyed on Nodes (which
        # always exist), so its requeue chain survives windows with no
        # plugin pods; pod deletions are healed by the periodic requeue.
        self.manager.add(
            Controller(
                "sim-device-plugin",
                self.kube,
                "Node",
                self._plugin_sim_reconcile,
            )
        )
        self.manager.add(
            Controller(
                "sim-scheduler",
                self.kube,
                "Pod",
                self._scheduler_sim_reconcile,
            )
        )

    # -------------------------------------------------------- plugin simulator

    def _plugin_sim_reconcile(self, request: Request) -> Result:
        """DaemonSet + device-plugin behavior: for every node, make sure a
        Running plugin pod exists and the node's slices are advertised."""
        with self._lock:
            plugin_pods = self.kube.list(
                "Pod",
                label_selector={
                    constants.DEVICE_PLUGIN_LABEL_KEY:
                        constants.DEVICE_PLUGIN_LABEL_VALUE
                },
            )
            nodes_with_plugin = {
                (p.get("spec") or {}).get("nodeName") for p in plugin_pods
            }
            for name, sim in self.nodes.items():
                if name not in nodes_with_plugin:
                    self._create_plugin_pod(name)
                sim.advertise_slices()
        return Result(requeue_after=self._report_interval)

    # ----------------------------------------------------- scheduler simulator

    def _scheduler_sim_reconcile(self, request: Request) -> Result:
        """kube-scheduler + kubelet behavior for slice-requesting pods."""
        try:
            pod = self.kube.get("Pod", request.name, request.namespace or None)
        except NotFound:
            return Result()
        if objects.pod_is_scheduled(pod) or not objects.pod_is_pending(pod):
            return Result()
        from walkai_nos_tpu.tpu.sharing.profile import (
            get_requested_shared_profiles,
            shared_profile_resource_name,
        )

        # Unified resource-name demand: tiling slices + chip-count shares.
        wanted: dict[str, int] = {
            constants.RESOURCE_TPU_SLICE_PREFIX + p: q
            for p, q in get_requested_profiles(pod).items()
        }
        for p, q in get_requested_shared_profiles(pod).items():
            wanted[shared_profile_resource_name(p)] = q
        if not wanted:
            return Result()
        with self._lock:
            for name, sim in self.nodes.items():
                free = self._free_devices(sim)
                chosen: list[Device] = []
                satisfiable = True
                for resource, qty in wanted.items():
                    matches = [
                        d
                        for d in free
                        if d.resource_name == resource and d not in chosen
                    ]
                    if len(matches) < qty:
                        satisfiable = False
                        break
                    chosen.extend(matches[:qty])
                if satisfiable:
                    for d in chosen:
                        sim.resources.mark_used(d.device_id)
                    # Bind via the pods/binding subresource (what
                    # kube-scheduler does; spec.nodeName is immutable on a
                    # real API server), then report the kubelet's phase.
                    self.kube.bind_pod(
                        request.name, request.namespace or "default", name
                    )
                    self.kube.patch_status(
                        "Pod",
                        request.name,
                        {
                            "status": {
                                "phase": "Running",
                                "conditions": [
                                    {"type": "PodScheduled", "status": "True"}
                                ],
                            },
                        },
                        request.namespace or "default",
                    )
                    return Result()
        # Unschedulable: record the condition so the partitioner reacts.
        if not objects.pod_is_unschedulable(pod):
            self.kube.patch_status(
                "Pod",
                request.name,
                {
                    "status": {
                        "conditions": [
                            {
                                "type": "PodScheduled",
                                "status": "False",
                                "reason": "Unschedulable",
                            }
                        ]
                    }
                },
                request.namespace or "default",
            )
        return Result(requeue_after=self._report_interval)

    def _free_devices(self, sim: SimNode) -> list[Device]:
        used = {d.device_id for d in sim.resources.get_used_devices()}
        return [
            d
            for d in sim.resources.get_allocatable_devices()
            if d.device_id not in used
        ]

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        self.wire_partitioner()
        self.manager.start()

    def stop(self) -> None:
        self.manager.stop()

    def __enter__(self) -> "SimCluster":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # --------------------------------------------------------------- helpers

    def create_slice_pod(
        self, name: str, profile: str, quantity: int = 1, namespace: str = "default"
    ) -> dict:
        return self._create_resource_pod(
            name,
            constants.RESOURCE_TPU_SLICE_PREFIX + profile,
            quantity,
            namespace,
        )

    def create_shared_pod(
        self, name: str, profile: str, quantity: int = 1, namespace: str = "default"
    ) -> dict:
        """A pod requesting a chip-count share, e.g. profile \"2c\"."""
        from walkai_nos_tpu.tpu.sharing.profile import (
            shared_profile_resource_name,
        )

        return self._create_resource_pod(
            name, shared_profile_resource_name(profile), quantity, namespace
        )

    def _create_resource_pod(
        self, name: str, resource: str, quantity: int, namespace: str
    ) -> dict:
        return self.kube.create(
            "Pod",
            {
                "metadata": {"name": name, "namespace": namespace},
                "spec": {
                    "containers": [
                        {
                            "name": "main",
                            "resources": {
                                "requests": {resource: str(quantity)},
                                "limits": {resource: str(quantity)},
                            },
                        }
                    ]
                },
                "status": {"phase": "Pending"},
            },
        )

"""Traffic-replay benchmark: diurnal load + flash crowds + Zipf
templates through the fleet router.

`sim/schedbench.py` replays pod-to-slice scheduling through the REAL
control plane; this module does the same for serving traffic through
the REAL router + engines (`router/core.py` over in-process
`ContinuousBatcher` replicas — tiny configs, CPU-friendly): a
deterministic trace of requests whose arrival rate follows a diurnal
curve with a flash-crowd surge window, and whose prompts draw from a
Zipf-distributed pool of templates (each template a shared
full-128-token-block prefix plus a per-request suffix — the
million-user serving shape where a handful of system prompts
dominate).

Headline keys (gated absent_ok in BASELINE.json, emitted by
`bench.py`'s router phase):

- `router_ttft_p99_under_surge` — p99 TTFT of requests that arrived
  inside the flash-crowd window (nearest-rank, `utils/stats`): the
  serving quality the router + autoscaler must defend exactly when
  load spikes;
- `router_prefix_hit_rate` — the fleet-level prefix-cache hit rate
  prefix-affinity routing exists to raise (compare
  `router_rr_prefix_hit_rate`, the same trace under round-robin:
  affinity should beat it because each template's blocks are warmed
  on ONE replica instead of sprayed across all);
- `router_scale_events_total` — reconciler actions during the
  replay (up + down) when autoscaling is enabled;
- `cb_prefill_100k_ttft_s` / `cb_short_p99_under_long_load` — the
  bimodal long-context arm (`run_long_context_benchmark`): one very
  long prompt beside a short-prompt stream through the sequence-
  parallel prefill lane, sp-on vs sp-off — long TTFT must improve,
  short p99 must hold;
- `router_obs_overhead_pct` — the fleet observability plane's cost
  (`measure_router_obs_overhead`: the same trace replayed with the
  router-side plane on vs off, engine telemetry on in both arms),
  gated at the same absolute < 2% budget as the engine's
  `obs_overhead_pct`;
- `router_canary_overhead_pct` / `router_canary_divergence_total` —
  the shadow plane's cost and correctness proof
  (`measure_canary_overhead`: the same trace replayed with a
  same-config canary mirroring 100% of submits vs no canary, arms
  interleaved per repeat; a same-weights mirror MUST produce zero
  digest divergences, and the primary-path tax is gated at the same
  absolute < 2% budget).

The trace is tick-based, not wall-clock-based: arrivals land at
router-step boundaries by largest-remainder apportionment of a
deterministic rate curve, so two runs over the same seed submit the
same requests in the same order — the property the affinity-vs-
round-robin comparison and the CI fleet test both need. TTFT values
are still real host seconds (the engines' own record clocks).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from walkai_nos_tpu.utils.stats import percentile

__all__ = [
    "TrafficBenchResult",
    "make_trace",
    "measure_canary_overhead",
    "measure_router_obs_overhead",
    "run_long_context_benchmark",
    "run_traffic_benchmark",
]


@dataclass
class TrafficBenchResult:
    requests: int
    completed: int
    errored: int
    ttft_p99_surge_s: float | None
    ttft_p99_steady_s: float | None
    prefix_hit_rate: float | None
    rr_prefix_hit_rate: float | None
    scale_up_events: int
    scale_down_events: int
    replicas_final: int
    per_request_tokens: dict = field(default_factory=dict)
    # Disaggregation arm (compare_disaggregated=True): the SAME trace
    # through a role-split prefill/decode fleet with block shipping
    # (the fleet-global prefix cache), and through a colocated
    # affinity fleet with shipping OFF (per-replica caches — the
    # pre-disaggregation baseline the global cache must beat).
    disagg_ttft_p99_s: float | None = None
    disagg_prefix_hit_rate: float | None = None
    disagg_completed: int | None = None
    noship_prefix_hit_rate: float | None = None
    disagg_per_request_tokens: dict = field(default_factory=dict)

    def bench_keys(self) -> dict:
        """The headline-key view `bench.py` merges into its one JSON
        line (names match BASELINE.json's published specs)."""
        out = {
            "router_requests": self.requests,
            "router_completed": self.completed,
            "router_errored": self.errored,
            "router_scale_events_total": (
                self.scale_up_events + self.scale_down_events
            ),
            "router_scale_up_events": self.scale_up_events,
            "router_scale_down_events": self.scale_down_events,
            "router_replicas_final": self.replicas_final,
        }
        if self.ttft_p99_surge_s is not None:
            out["router_ttft_p99_under_surge"] = round(
                self.ttft_p99_surge_s, 4
            )
        if self.ttft_p99_steady_s is not None:
            out["router_ttft_p99_steady"] = round(
                self.ttft_p99_steady_s, 4
            )
        if self.prefix_hit_rate is not None:
            out["router_prefix_hit_rate"] = round(
                self.prefix_hit_rate, 4
            )
        if self.rr_prefix_hit_rate is not None:
            out["router_rr_prefix_hit_rate"] = round(
                self.rr_prefix_hit_rate, 4
            )
        if self.disagg_ttft_p99_s is not None:
            out["router_disagg_ttft_p99"] = round(
                self.disagg_ttft_p99_s, 4
            )
        if self.disagg_prefix_hit_rate is not None:
            out["router_disagg_prefix_hit_rate"] = round(
                self.disagg_prefix_hit_rate, 4
            )
        if self.noship_prefix_hit_rate is not None:
            out["router_noship_prefix_hit_rate"] = round(
                self.noship_prefix_hit_rate, 4
            )
        return out


def make_trace(
    *,
    requests: int,
    templates: int,
    ticks: int,
    zipf_a: float = 1.1,
    surge_start_frac: float = 0.5,
    surge_len_frac: float = 0.25,
    surge_mult: float = 4.0,
    suffix_tokens: int = 8,
    max_new: int = 6,
    vocab: int = 64,
    prefix_tokens: int = 128,
    seed: int = 0,
) -> tuple[list[list[dict]], set[int]]:
    """(arrivals per tick, surge tick set). Each arrival is
    {"prompt": np.ndarray, "template": t, "max_new": n}; prompts are
    a Zipf-chosen shared `prefix_tokens` template prefix + a random
    suffix, deterministically derived from `seed`."""
    rng = np.random.default_rng(seed)
    prefixes = [
        rng.integers(0, vocab, prefix_tokens).astype(np.int32)
        for _ in range(templates)
    ]
    weights = 1.0 / np.arange(1, templates + 1) ** zipf_a
    weights /= weights.sum()
    # Diurnal rate curve with a flash-crowd window on top.
    s0 = int(ticks * surge_start_frac)
    s1 = min(ticks, s0 + max(1, int(ticks * surge_len_frac)))
    surge_ticks = set(range(s0, s1))
    rate = np.sin(np.pi * (np.arange(ticks) + 0.5) / ticks) ** 2 + 0.2
    for t in surge_ticks:
        rate[t] *= surge_mult
    # Largest-remainder apportionment of exactly `requests` arrivals.
    share = rate / rate.sum() * requests
    counts = np.floor(share).astype(int)
    remainder = requests - int(counts.sum())
    for t in np.argsort(share - counts)[::-1][:remainder]:
        counts[t] += 1
    trace: list[list[dict]] = []
    for t in range(ticks):
        arrivals = []
        for _ in range(int(counts[t])):
            template = int(rng.choice(templates, p=weights))
            suffix = rng.integers(0, vocab, suffix_tokens).astype(
                np.int32
            )
            arrivals.append({
                "prompt": np.concatenate(
                    [prefixes[template], suffix]
                ),
                "template": template,
                "max_new": max_new,
            })
        trace.append(arrivals)
    return trace, surge_ticks


def default_engine_factory(cfg=None, params=None, *, slots=4,
                           cache_len=256, chunk_steps=4,
                           park_blocks=8, prefill_lanes=1):
    """(cfg, params, factory): tiny-config in-process engines sharing
    ONE weight set — routing must never change tokens, so every
    replica serves the same model. `park_blocks` of pool headroom
    beyond the per-slot worst case let released template prefixes
    PARK in the radix index instead of being evicted between reuses
    — without it a tiny pool's eviction pressure (and its pinned
    `pool` saturation component) would measure the allocator, not
    the routing policy. `prefill_lanes=1` serializes admissions so a
    same-template request admitted right behind its writer finds the
    writer's blocks READY (the trie marks a block matchable only
    once its writing chunk has dispatched): with concurrent lanes
    the hit/miss split would partly measure admission-window
    collisions — timing noise — instead of the routing policy, and
    it halves the per-engine XLA compile surface too."""
    import jax

    from walkai_nos_tpu.models.lm import DecoderLM, LMConfig
    from walkai_nos_tpu.ops.decode_attention import PAGE_ROWS

    if cfg is None:
        cfg = LMConfig(
            vocab_size=64, hidden_dim=32, num_layers=1, num_heads=2,
            max_seq_len=512,
        )
    if params is None:
        params = DecoderLM(cfg).init_params(jax.random.PRNGKey(0))
    pool_blocks = (
        slots * -(-cache_len // PAGE_ROWS) + 1 + park_blocks
    )

    def factory(name: str):
        from walkai_nos_tpu.models.serve import ContinuousBatcher
        from walkai_nos_tpu.router.replica import EngineReplica

        return EngineReplica(
            ContinuousBatcher(
                cfg, params, slots=slots, cache_len=cache_len,
                chunk_steps=chunk_steps, pool_blocks=pool_blocks,
                prefill_lanes=prefill_lanes,
            ),
            name=name,
        )

    return cfg, params, factory


def _warm(replica) -> None:
    """Compile an in-process engine's programs OFF the replay path
    (`EngineReplica.warm()` — the demo server's warmup discipline).
    Without this the trace's first arrivals measure XLA compile, not
    serving — a ~60 s TTFT outlier on a CPU dev box. HTTP replicas
    warm on their own server's startup (`warm()` is a no-op)."""
    replica.warm()


def _replay(router, trace, surge_ticks) -> tuple[dict, dict, int]:
    """Drive the trace through a router: returns (records by rid,
    submit tick by rid, errored count)."""
    records: dict[int, dict] = {}
    submit_tick: dict[int, int] = {}
    errored = 0
    for tick, arrivals in enumerate(trace):
        for arrival in arrivals:
            try:
                rid = router.submit(
                    arrival["prompt"],
                    max_new_tokens=arrival["max_new"],
                )
            except (ValueError, RuntimeError):
                errored += 1
                continue
            submit_tick[rid] = tick
        router.step()
        records.update(router.drain_done_records())
    while router.has_work:
        router.step()
        records.update(router.drain_done_records())
    records.update(router.drain_done_records())
    return records, submit_tick, errored


def run_traffic_benchmark(
    *,
    n_replicas: int = 2,
    spare_replicas: int = 0,
    requests: int = 64,
    templates: int = 6,
    ticks: int = 32,
    zipf_a: float = 1.1,
    slots: int = 4,
    max_new: int = 6,
    seed: int = 0,
    compare_round_robin: bool = True,
    compare_disaggregated: bool = False,
    scale_policy=None,
    cfg=None,
    params=None,
) -> TrafficBenchResult:
    """Replay one deterministic trace through a prefix-affinity fleet
    (optionally autoscaling over `spare_replicas` provider-held
    spares) and, for the hit-rate comparison, through a fresh
    round-robin fleet on the SAME trace and weights.

    `compare_disaggregated=True` adds two more arms on the same
    trace: a role-split fleet (half the replicas prefill-only, half
    decode-only; streams migrate at first token, KV blocks ship with
    placement — the fleet-global prefix cache), and a colocated
    affinity fleet with block shipping OFF (per-replica caches, the
    pre-disaggregation baseline). Emitted as
    `router_disagg_ttft_p99`, `router_disagg_prefix_hit_rate` and
    `router_noship_prefix_hit_rate`."""
    from walkai_nos_tpu.router.autoscale import StaticSliceProvider
    from walkai_nos_tpu.router.core import FleetRouter

    cfg, params, factory = default_engine_factory(
        cfg, params, slots=slots
    )
    trace, surge_ticks = make_trace(
        requests=requests, templates=templates, ticks=ticks,
        zipf_a=zipf_a, max_new=max_new, vocab=cfg.vocab_size,
        seed=seed,
    )

    replicas = [factory(f"r{i}") for i in range(n_replicas)]
    spares = [factory(f"spare{i}") for i in range(spare_replicas)]
    for replica in replicas + spares:
        _warm(replica)
    provider = (
        StaticSliceProvider(spares) if spare_replicas > 0 else None
    )
    # Straggler detection OFF for the policy comparison: this replay
    # measures the ROUTING POLICY (affinity vs round-robin hit rate
    # on one deterministic trace), and tiny CPU replicas' timing
    # spread is load imbalance, not hardware degradation — a
    # noise-driven flag would migrate templates mid-comparison and
    # measure the detector instead. The fleet plane's own cost is
    # measured separately by `measure_router_obs_overhead` (full
    # plane on vs off).
    router = FleetRouter(
        replicas, provider=provider, scale_policy=scale_policy,
        policy="affinity", seed=seed, anomaly=False,
    )
    records, submit_tick, errored = _replay(
        router, trace, surge_ticks
    )

    surge_ttft = sorted(
        r["ttft_s"] for rid, r in records.items()
        if submit_tick.get(rid) in surge_ticks
        and r.get("ttft_s") is not None
    )
    steady_ttft = sorted(
        r["ttft_s"] for rid, r in records.items()
        if submit_tick.get(rid) not in surge_ticks
        and r.get("ttft_s") is not None
    )
    events = router.scale_events()

    rr_rate = None
    if compare_round_robin:
        rr_replicas = [
            factory(f"rr{i}") for i in range(n_replicas)
        ]
        for replica in rr_replicas:
            _warm(replica)
        rr_router = FleetRouter(
            rr_replicas, policy="round_robin", seed=seed,
            anomaly=False,
        )
        _replay(rr_router, trace, surge_ticks)
        rr_rate = rr_router.prefix_hit_rate

    disagg_ttft = None
    disagg_rate = None
    disagg_completed = None
    disagg_tokens: dict = {}
    noship_rate = None
    if compare_disaggregated and n_replicas >= 2:
        # Role-split fleet: prefill-only members take every new
        # request (pure load placement), decode-only members receive
        # each stream at first token (KV blocks + sampler state ride
        # the migration payload). Block shipping keeps the prefill
        # tries warm wherever placement lands a template.
        n_prefill = (n_replicas + 1) // 2
        dis_router = FleetRouter(seed=seed, anomaly=False)
        for i in range(n_replicas):
            replica = factory(f"d{i}")
            _warm(replica)
            dis_router.add_replica(
                replica,
                role="prefill" if i < n_prefill else "decode",
            )
        dis_records, _ticks, _err = _replay(
            dis_router, trace, surge_ticks
        )
        dis_ttft = sorted(
            r["ttft_s"] for r in dis_records.values()
            if r.get("ttft_s") is not None
        )
        disagg_ttft = percentile(dis_ttft, 99)
        disagg_rate = dis_router.prefix_hit_rate
        disagg_completed = len(dis_records)
        disagg_tokens = {
            rid: rec["tokens"] for rid, rec in dis_records.items()
        }
        # The per-replica-cache baseline: same colocated affinity
        # policy, shipping OFF — every replica pays its own cold
        # prefill per template.
        ns_replicas = [
            factory(f"ns{i}") for i in range(n_replicas)
        ]
        for replica in ns_replicas:
            _warm(replica)
        ns_router = FleetRouter(
            ns_replicas, policy="affinity", ship_blocks=False,
            seed=seed, anomaly=False,
        )
        _replay(ns_router, trace, surge_ticks)
        noship_rate = ns_router.prefix_hit_rate

    return TrafficBenchResult(
        requests=sum(len(a) for a in trace),
        completed=len(records),
        errored=errored,
        ttft_p99_surge_s=percentile(surge_ttft, 99),
        ttft_p99_steady_s=percentile(steady_ttft, 99),
        prefix_hit_rate=router.prefix_hit_rate,
        rr_prefix_hit_rate=rr_rate,
        scale_up_events=events["up"],
        scale_down_events=events["down"],
        replicas_final=len(router.replicas),
        per_request_tokens={
            rid: rec["tokens"] for rid, rec in records.items()
        },
        disagg_ttft_p99_s=disagg_ttft,
        disagg_prefix_hit_rate=disagg_rate,
        disagg_completed=disagg_completed,
        noship_prefix_hit_rate=noship_rate,
        disagg_per_request_tokens=disagg_tokens,
    )


def run_long_context_benchmark(
    *,
    slots: int = 4,
    short_requests: int = 12,
    short_tokens: int = 24,
    long_tokens: int = 320,
    sp_min_tokens: int = 256,
    sp_span: int = 0,
    prefill_chunk: int = 64,
    prefill_lanes: int = 4,
    cache_len: int = 512,
    max_new: int = 4,
    shorts_per_step: int = 2,
    seed: int = 0,
    cfg=None,
    params=None,
) -> dict:
    """Bimodal 1k/100k arm for the sequence-parallel prefill lane:
    ONE long prompt (`long_tokens`, >= `sp_min_tokens` — the CPU-
    scaled stand-in for a 100k-token context) submitted ahead of a
    stream of short prompts, replayed through two otherwise-identical
    engines — sp ON and sp OFF — on the same deterministic prompts.

    Headline keys (absent_ok in BASELINE.json):

    - `cb_prefill_100k_ttft_s` — the long prompt's TTFT with sp ON
      (its chunk windows fan out across lane rows, so prefill takes
      ~windows/span dispatches instead of one per window);
    - `cb_short_p99_under_long_load` — p99 TTFT of the short prompts
      admitted WHILE the long prompt prefills, sp ON: the fairness
      half of the contract (length-aware admission must keep short-
      prompt latency within a few percent of the sp-off engine even
      as the long prompt takes its spare rows);
    - `cb_prefill_100k_ttft_sp_off_s` / `cb_short_p99_sp_off` — the
      same two numbers from the sp-OFF arm, the comparison floor.
    """
    import jax

    from walkai_nos_tpu.models.lm import DecoderLM, LMConfig
    from walkai_nos_tpu.models.serve import ContinuousBatcher
    from walkai_nos_tpu.ops.decode_attention import PAGE_ROWS

    if cfg is None:
        cfg = LMConfig(
            vocab_size=64, hidden_dim=32, num_layers=1, num_heads=2,
            max_seq_len=max(512, cache_len),
        )
    if params is None:
        params = DecoderLM(cfg).init_params(jax.random.PRNGKey(0))
    rng = np.random.default_rng(seed)
    long_prompt = rng.integers(
        0, cfg.vocab_size, long_tokens
    ).astype(np.int32)
    shorts = [
        rng.integers(0, cfg.vocab_size, short_tokens).astype(np.int32)
        for _ in range(short_requests)
    ]
    pool_blocks = slots * -(-cache_len // PAGE_ROWS) + 1 + 8

    def one_arm(sp: bool) -> tuple[float | None, float | None]:
        eng = ContinuousBatcher(
            cfg, params, slots=slots, cache_len=cache_len,
            paged=True, pool_blocks=pool_blocks,
            prefill_chunk=prefill_chunk,
            prefill_lanes=prefill_lanes,
            sp_prefill=sp, sp_min_tokens=sp_min_tokens,
            sp_span=sp_span,
            # The arm measures prefill COMPUTE fan-out; with the
            # cache on, the warm pass below would turn the timed
            # long prompt into a full prefix hit and measure nothing.
            prefix_cache=False,
        )
        eng.warm()
        # warm() covers the admission-burst widths but not the
        # multi-window lane shapes a long prompt drives (nor the sp
        # span fan-out); run the same prompt shapes through once,
        # discarded, so the timed phase measures steps, not XLA.
        eng.submit(long_prompt, max_new_tokens=1)
        eng.submit(shorts[0], max_new_tokens=1)
        eng.run()
        eng.drain_done_records()
        records: dict[int, dict] = {}
        long_rid = eng.submit(long_prompt, max_new_tokens=max_new)
        pending = list(shorts)
        while pending or eng.has_work:
            for _ in range(shorts_per_step):
                if pending:
                    eng.submit(
                        pending.pop(0), max_new_tokens=max_new
                    )
            eng.step()
            records.update(eng.drain_done_records())
        records.update(eng.drain_done_records())
        long_ttft = records.get(long_rid, {}).get("ttft_s")
        short_ttfts = sorted(
            r["ttft_s"] for rid, r in records.items()
            if rid != long_rid and r.get("ttft_s") is not None
        )
        return long_ttft, percentile(short_ttfts, 99)

    off_long, off_short = one_arm(False)
    on_long, on_short = one_arm(True)
    out: dict = {}
    if on_long is not None:
        out["cb_prefill_100k_ttft_s"] = round(on_long, 4)
    if on_short is not None:
        out["cb_short_p99_under_long_load"] = round(on_short, 4)
    if off_long is not None:
        out["cb_prefill_100k_ttft_sp_off_s"] = round(off_long, 4)
    if off_short is not None:
        out["cb_short_p99_sp_off"] = round(off_short, 4)
    return out


def measure_router_obs_overhead(
    *,
    n_replicas: int = 2,
    requests: int = 48,
    templates: int = 4,
    ticks: int = 24,
    slots: int = 4,
    max_new: int = 6,
    repeats: int = 3,
    seed: int = 0,
    fleet_refresh_s: float = 1.0,
    cfg=None,
    params=None,
) -> dict:
    """A/B of the FLEET observability plane's cost: the same
    deterministic trace replayed through fresh fleets with the plane
    fully ON (router registry + request spans + throttled
    anomaly/signal refresh + scrape/federation bookkeeping) vs fully
    OFF (`FleetRouter(obs=False)` — no-op registry, disabled trace,
    no detector), arms interleaved per repeat, median wall seconds
    each. Engine-side telemetry stays ON in BOTH arms — the engine's
    own budget is `obs_overhead_pct`; this key isolates the
    router-layer addition and is gated at the same absolute < 2%
    budget in BASELINE.json. The ON arm runs the PRODUCTION refresh
    throttle (`fleet_refresh_s`, default 1 s — the budget gates the
    configuration that ships, not an artificial per-step worst
    case)."""
    cfg, params, factory = default_engine_factory(
        cfg, params, slots=slots
    )
    trace, _ = make_trace(
        requests=requests, templates=templates, ticks=ticks,
        max_new=max_new, vocab=cfg.vocab_size, seed=seed,
    )
    from walkai_nos_tpu.router.core import FleetRouter

    seq = [0]

    def one_replay(enabled: bool) -> float:
        arm = "on" if enabled else "off"
        replicas = [
            factory(f"obs-{arm}{seq[0]}-{i}")
            for i in range(n_replicas)
        ]
        seq[0] += 1
        for replica in replicas:
            _warm(replica)
        router = FleetRouter(
            replicas, policy="affinity", seed=seed,
            obs=enabled, fleet_refresh_s=fleet_refresh_s,
        )
        t0 = time.perf_counter()
        _replay(router, trace, set())
        return time.perf_counter() - t0

    walls: dict[bool, list[float]] = {True: [], False: []}
    for _ in range(max(1, repeats)):
        for enabled in (True, False):
            walls[enabled].append(one_replay(enabled))
    on = sorted(walls[True])[len(walls[True]) // 2]
    off = sorted(walls[False])[len(walls[False]) // 2]
    return {
        "router_obs_overhead_pct": round(
            100.0 * (on - off) / max(off, 1e-9), 2
        ),
        "router_obs_on_wall_s": round(on, 4),
        "router_obs_off_wall_s": round(off, 4),
    }


def measure_canary_overhead(
    *,
    n_replicas: int = 2,
    requests: int = 48,
    templates: int = 4,
    ticks: int = 24,
    slots: int = 4,
    max_new: int = 6,
    repeats: int = 3,
    seed: int = 0,
    cfg=None,
    params=None,
) -> dict:
    """A/B of the shadow plane's primary-path cost AND its
    correctness invariant in one measurement: the same deterministic
    trace replayed through fresh fleets with a SAME-CONFIG canary
    mirroring 100% of submits vs no canary at all, arms interleaved
    per repeat, median wall seconds each. The canary replica serves
    the same weights and knobs as the fleet, so the digest gate is
    armed and every mirrored pair must match token-for-token —
    `router_canary_divergence_total` is emitted and MUST be 0 (a
    nonzero value means the mirror seam itself changes tokens, which
    would make every real canary verdict meaningless).

    The budgeted key is the ROUTER-PLANE tax only (mirror submit +
    capture bookkeeping on the submit path, pairing + crc32 compare
    at the completion seam, per-step verdict evaluation). In
    production engine compute rides accelerators — the canary's on a
    device that serves no user traffic — but this in-process harness
    steps every engine serially inside `router.step()`, so engine
    `step()` time is measured separately (timed wrappers on every
    replica, both arms) and subtracted: overhead =
    (on_plane_wall - off_plane_wall) / off_total_wall, where
    plane_wall = total_wall - engine_step_wall. Without the
    subtraction the key would mostly measure the canary's decode
    compute and the idle primary steps taken while the drain loop
    waits for the last mirrors — neither exists on real hardware.
    Gated at the same absolute < 2% budget as
    `router_obs_overhead_pct`."""
    cfg, params, factory = default_engine_factory(
        cfg, params, slots=slots
    )
    trace, _ = make_trace(
        requests=requests, templates=templates, ticks=ticks,
        max_new=max_new, vocab=cfg.vocab_size, seed=seed,
    )
    from walkai_nos_tpu.router.core import FleetRouter

    seq = [0]
    divergences = [0]
    compared = [0]

    def one_replay(mirrored: bool) -> tuple[float, float]:
        arm = "on" if mirrored else "off"
        replicas = [
            factory(f"cny-{arm}{seq[0]}-{i}")
            for i in range(n_replicas)
        ]
        canary = factory(f"cny-{arm}{seq[0]}-c") if mirrored else None
        seq[0] += 1
        engine_step_s = [0.0]

        def timed(replica):
            # Bill engine compute to the engines (accelerators in
            # production, serial host work here), both arms.
            orig_step = replica.step

            def timed_step():
                t = time.perf_counter()
                orig_step()
                engine_step_s[0] += time.perf_counter() - t

            replica.step = timed_step
            return replica

        for replica in replicas + ([canary] if canary else []):
            _warm(replica)
            timed(replica)
        router = FleetRouter(
            replicas, policy="affinity", seed=seed,
            canary_mirror=1.0,
        )
        if canary is not None:
            router.add_replica(canary, role="canary")
        t0 = time.perf_counter()
        _replay(router, trace, set())
        wall = time.perf_counter() - t0
        if canary is not None:
            stats = router.canary_stats()
            divergences[0] += stats["divergences"]
            compared[0] += stats["compared"]
        return wall - engine_step_s[0], wall

    plane: dict[bool, list[float]] = {True: [], False: []}
    total: dict[bool, list[float]] = {True: [], False: []}
    for _ in range(max(1, repeats)):
        for mirrored in (True, False):
            plane_wall, wall = one_replay(mirrored)
            plane[mirrored].append(plane_wall)
            total[mirrored].append(wall)
    on = sorted(plane[True])[len(plane[True]) // 2]
    off = sorted(plane[False])[len(plane[False]) // 2]
    off_total = sorted(total[False])[len(total[False]) // 2]
    return {
        "router_canary_overhead_pct": round(
            100.0 * (on - off) / max(off_total, 1e-9), 2
        ),
        "router_canary_divergence_total": divergences[0],
        "router_canary_compared_total": compared[0],
        "router_canary_on_plane_wall_s": round(on, 4),
        "router_canary_off_plane_wall_s": round(off, 4),
        "router_canary_off_wall_s": round(off_total, 4),
    }

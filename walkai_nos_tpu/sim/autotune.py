"""Replay-driven engine autotune seed: one captured serving window
re-executed across a grid of determinism-preserving engine overrides,
every arm digest-verified before its throughput counts.

The capture plane (`obs/capture.py` + `sim/replay.py`) records live
traffic as a pure-function workload — (weights, prompt, knobs, seed)
per request, plus the token digests the original engine produced.
That makes a capture the safest possible tuning corpus: an override
arm that changes token values is not a "different quality point", it
is WRONG (every grid axis here is an ENGINE_KNOBS axis, proven
token-preserving by the replay matrix), so `autotune_capture` replays
the same window once per arm, verifies every completion against the
captured digests, and only digest-clean arms compete on replayed
throughput.

The output is a seed, not a closed loop: a Pareto table over
(replayed tokens/s up, divergent requests down) plus the headline
`autotune_capacity_gain_pct` — the best VERIFIED arm's throughput
gain over the capture's own config. Wiring the winning overrides into
a restart (or a canary: `serverouter --canary-override KEY=VALUE`
mirrors live traffic through the candidate config with the digest
gate armed, `obs/canary.py`) stays an operator decision.

Grid axes (`default_grid`): `loop_steps` (host<->device chat cadence),
`prefill_chunk` (prefill slice size), and — when the capture ran
speculative decoding — `spec_k` (draft depth). Neighbor values around
the captured config, one knob per arm: an axis sweep localizes any
win/regression to a single knob, which is what an operator acting on
the table needs.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

__all__ = [
    "ArmResult",
    "AutotuneReport",
    "autotune_capture",
    "default_grid",
]


@dataclass
class ArmResult:
    """One override arm's replay outcome. `ok` means every completed
    request matched the captured digests — only ok arms are eligible
    for the capacity headline."""

    overrides: dict
    wall_s: float = 0.0
    tokens: int = 0
    tokens_per_s: float = 0.0
    verified: int = 0
    divergent: int = 0
    ok: bool = False
    error: str | None = None  # arm could not run (bad override)

    def label(self) -> str:
        if not self.overrides:
            return "baseline"
        return ",".join(
            f"{k}={v}" for k, v in sorted(self.overrides.items())
        )


@dataclass
class AutotuneReport:
    fingerprint_id: str | None
    arms: list[ArmResult] = field(default_factory=list)  # [0]=baseline
    replay_wall_s: float = 0.0

    @property
    def baseline(self) -> ArmResult:
        return self.arms[0]

    def pareto(self) -> list[ArmResult]:
        """Arms no other arm dominates on (tokens/s up, divergent
        down). Errored arms never make the frontier."""
        ran = [a for a in self.arms if a.error is None]
        front = []
        for a in ran:
            dominated = any(
                b is not a
                and b.tokens_per_s >= a.tokens_per_s
                and b.divergent <= a.divergent
                and (
                    b.tokens_per_s > a.tokens_per_s
                    or b.divergent < a.divergent
                )
                for b in ran
            )
            if not dominated:
                front.append(a)
        return front

    def best(self) -> ArmResult | None:
        """Highest-throughput arm among those that digest-verified."""
        ok = [a for a in self.arms if a.ok]
        return max(ok, key=lambda a: a.tokens_per_s) if ok else None

    def capacity_gain_pct(self) -> float | None:
        """Best verified arm's replayed-throughput gain over the
        capture's own config; None when the baseline itself failed to
        verify (nothing to gain against). 0.0 when no override beats
        the baseline — never negative: shipping the captured config
        unchanged is always on the menu."""
        if not self.arms or not self.baseline.ok:
            return None
        best = self.best()
        base = self.baseline.tokens_per_s
        if best is None or base <= 0:
            return None
        return max(
            0.0, round(100.0 * (best.tokens_per_s - base) / base, 2)
        )

    def table(self) -> str:
        """The Pareto table, one printable line per arm."""
        front = {id(a) for a in self.pareto()}
        rows = [
            f"{'arm':<28} {'tok/s':>8} {'verified':>8} "
            f"{'divergent':>9} {'ok':>3} {'pareto':>6}"
        ]
        for a in self.arms:
            if a.error is not None:
                rows.append(f"{a.label():<28} ERROR: {a.error}")
                continue
            rows.append(
                f"{a.label():<28} {a.tokens_per_s:>8.1f} "
                f"{a.verified:>8d} {a.divergent:>9d} "
                f"{'y' if a.ok else 'n':>3} "
                f"{'*' if id(a) in front else '':>6}"
            )
        return "\n".join(rows)

    def summary(self) -> dict:
        """The headline-key view `bench.py` merges into its one JSON
        line (names match BASELINE.json's published specs)."""
        best = self.best()
        gain = self.capacity_gain_pct()
        out = {
            "autotune_arms": len(self.arms),
            "autotune_divergent_arms": sum(
                1 for a in self.arms if a.error is None and not a.ok
            ),
            "autotune_baseline_tokens_per_s": round(
                self.baseline.tokens_per_s, 1
            ) if self.arms else None,
            "autotune_best_overrides": (
                dict(best.overrides) if best else None
            ),
            "autotune_wall_s": round(self.replay_wall_s, 2),
        }
        if gain is not None:
            out["autotune_capacity_gain_pct"] = gain
        return out


def default_grid(fingerprint: dict) -> list[dict]:
    """Single-knob neighbor arms around the capture's own engine
    config: loop_steps and prefill_chunk at half/double the captured
    value, spec_k +/-2 when the capture ran speculative decoding.
    Arms equal to the captured value are dropped (the baseline
    already covers them)."""
    engine = dict((fingerprint or {}).get("engine") or {})
    arms: list[dict] = []

    def neighbors(knob, values, floor=1):
        current = engine.get(knob)
        if current is None:
            return
        for value in values:
            value = max(floor, int(value))
            if value != current:
                arm = {knob: value}
                if arm not in arms:
                    arms.append(arm)

    loop = int(engine.get("loop_steps") or 1)
    neighbors("loop_steps", (loop // 2, loop * 2))
    chunk = engine.get("prefill_chunk")
    if chunk:
        neighbors("prefill_chunk", (chunk // 2, chunk * 2), floor=8)
    if engine.get("spec"):
        k = int(engine.get("spec_k") or 1)
        neighbors("spec_k", (k - 2, k + 2))
    return arms


def autotune_capture(
    capture,
    params,
    *,
    arms: list[dict] | None = None,
) -> AutotuneReport:
    """Replay `capture` once per override arm (plus the no-override
    baseline), digest-verify every arm, and rank. Each arm rebuilds
    its engine from the capture's fingerprint + overrides — the same
    construction path `cmd/replay.py` uses, so an arm's verdict here
    predicts a `--override` replay's verdict exactly. An arm whose
    override the engine rejects (e.g. a prefill_chunk the pool cannot
    back) is kept in the table as an ERROR row, never silently
    dropped."""
    from walkai_nos_tpu.sim.replay import replay_capture

    if arms is None:
        arms = default_grid(capture.fingerprint)
    report = AutotuneReport(fingerprint_id=capture.fingerprint_id)
    t0 = time.monotonic()
    for overrides in [{}] + list(arms):
        arm = ArmResult(overrides=dict(overrides))
        try:
            t_arm = time.monotonic()
            rep = replay_capture(
                capture, params, overrides=overrides, timing="asap",
            )
            arm.wall_s = time.monotonic() - t_arm
        except (ValueError, RuntimeError) as bad:
            arm.error = str(bad)
            report.arms.append(arm)
            continue
        arm.tokens = sum(
            len(o.tokens)
            for o in rep.outcomes.values()
            if o.tokens is not None
        )
        arm.verified = rep.n_verified
        arm.divergent = len(rep.divergent)
        arm.ok = rep.ok and rep.n_verified > 0
        arm.tokens_per_s = (
            arm.tokens / arm.wall_s if arm.wall_s > 0 else 0.0
        )
        report.arms.append(arm)
    report.replay_wall_s = time.monotonic() - t0
    return report

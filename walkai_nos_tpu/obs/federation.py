"""Metrics federation + fleet trace merge for the router plane.

Two fleet-level read paths the serverouter serves, both built from
surfaces that already exist per replica:

- **Metrics federation** (`parse_exposition` / `federate`): every
  replica already renders its engine series as Prometheus 0.0.4 text
  (`ServingObs.render()` in process, `GET /metrics` over HTTP). The
  federator parses each replica's exposition, keeps the series whose
  names start with a **federated prefix** (`FEDERATED_PREFIXES` —
  the engine's `cb_*` family; `hack/metrics_lint.py` holds this
  tuple and docs/observability.md to each other in both directions),
  injects a `replica` label, and re-renders ONE merged exposition —
  so a single serverouter scrape carries the whole fleet's engine
  telemetry instead of N per-pod scrapes an operator must aggregate
  by hand. A replica-supplied `replica` label is overwritten, never
  trusted: the router's handle name is the identity. Retired
  replicas simply stop being sources, so their series drop from the
  very next render — the same dead-pods-never-export-as-live
  discipline as `Gauge.remove`.
- **Fleet trace merge** (`merge_fleet_trace`): the router's own spans
  (`obs/trace.RouterTrace`) and each replica's Chrome trace export
  (`RequestTrace.chrome_trace`) are per-process timelines on
  per-process monotonic clocks. Every export carries its clock
  origin (`otherData.clock_origin_monotonic_s` — the absolute
  monotonic second its relative microsecond timestamps count from),
  and each remote replica's clock offset vs the router is estimated
  from the `/healthz` probe that already runs (offset = the payload's
  `monotonic_s` minus the probe's send/receive RTT midpoint —
  NTP-style, accurate to ~RTT/2). The merge re-bases every event
  into the ROUTER clock frame, assigns one Chrome process per
  source, and sorts — one Perfetto-loadable timeline where a
  request's route -> queue -> prefill -> first-token path crosses
  process boundaries under one trace id.

Stdlib-only on purpose: `hack/metrics_lint.py` imports this module's
`FEDERATED_PREFIXES` from doc-only CI, like the catalog.
"""

from __future__ import annotations

import re

from walkai_nos_tpu.obs.metrics import _fmt, escape_label

__all__ = [
    "FEDERATED_PREFIXES",
    "federate",
    "first_value",
    "merge_fleet_trace",
    "parse_exposition",
]

# Engine series re-exported by the serverouter's /metrics under a
# `replica` label. The lint holds this tuple and the docs' "Federated
# prefixes:" line to each other in both directions, and rejects any
# catalog metric that would collide (a `replica` label belongs to the
# router component only — engines must never self-label).
FEDERATED_PREFIXES: tuple[str, ...] = ("cb_",)

_SAMPLE = re.compile(
    r"^([A-Za-z_:][A-Za-z0-9_:]*)"      # sample name
    r"(?:\{(.*)\})?"                     # optional label block
    # Value: the '-' inside the class covers negative EXPONENTS too
    # (repr of |v| < 1e-4 renders as e.g. 5e-05 — a fast replica's
    # sub-100µs dispatch p99 must not silently vanish from the
    # federation).
    r" (-?[0-9.eE+-]+|NaN|[+-]Inf)$"
)
_LABEL = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(value: str) -> str:
    return (
        value.replace("\\n", "\n")
        .replace('\\"', '"')
        .replace("\\\\", "\\")
    )


def parse_exposition(text: str) -> dict[str, dict]:
    """Prometheus 0.0.4 text -> {family name: {"kind", "help",
    "samples": [(sample name, labels dict, value)]}}.

    The `_parse_value`-style inverse of `Registry.render` (and the
    demo server's /metrics): `# TYPE`/`# HELP` comments open a metric
    family; following sample lines attach to it (histogram `_bucket`/
    `_sum`/`_count` suffixes included, since their names extend the
    family's). A sample with no preceding family opens an implicit
    untyped one. Families render contiguously in this repo's
    exposition, which is the only format the federator consumes."""
    families: dict[str, dict] = {}
    current: str | None = None

    def family(name: str, kind: str = "untyped") -> dict:
        return families.setdefault(
            name, {"kind": kind, "help": "", "samples": []}
        )

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            if len(parts) >= 3:
                current = parts[2]
                family(current)["help"] = (
                    parts[3] if len(parts) > 3 else ""
                )
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ", 3)
            if len(parts) >= 4:
                current = parts[2]
                family(current)["kind"] = parts[3]
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE.match(line)
        if m is None:
            continue
        name, label_blob, raw = m.groups()
        labels = {
            k: _unescape(v)
            for k, v in _LABEL.findall(label_blob or "")
        }
        try:
            value = float(raw.replace("Inf", "inf"))
        except ValueError:
            continue
        if current is not None and (
            name == current or name.startswith(current + "_")
        ):
            families[current]["samples"].append((name, labels, value))
        else:
            current = name
            family(name)["samples"].append((name, labels, value))
    return families


def first_value(text: str, name: str) -> float | None:
    """First sample value of an UNLABELED series `name` in a text
    exposition; None when absent (bench_lm's `_parse_value` shape —
    the parse the HttpReplica signal reads use)."""
    m = re.search(
        rf"^{re.escape(name)} (-?[0-9.eE+-]+|NaN|[+-]Inf)$",
        text, re.MULTILINE,
    )
    if m is None:
        return None
    try:
        return float(m.group(1).replace("Inf", "inf"))
    except ValueError:
        return None


def _render_labels(labels: dict) -> str:
    if not labels:
        return ""
    parts = [
        f'{k}="{escape_label(v)}"' for k, v in sorted(labels.items())
    ]
    return "{" + ",".join(parts) + "}"


def federate(
    sources: dict[str, str],
    *,
    prefixes: tuple[str, ...] = FEDERATED_PREFIXES,
    label: str = "replica",
) -> str:
    """Merge per-replica expositions into one, each series tagged
    `{replica="<name>"}`. Only families whose name starts with a
    federated prefix ride through (router_* and anything else a
    source might carry stays the source's own); HELP/TYPE render once
    per family (first source's wins), sources render in name order so
    the output is deterministic. Empty when no source carries a
    federated family."""
    merged: dict[str, dict] = {}
    for replica in sorted(sources):
        for name, fam in parse_exposition(sources[replica]).items():
            if not any(name.startswith(p) for p in prefixes):
                continue
            slot = merged.setdefault(
                name,
                {"kind": fam["kind"], "help": fam["help"], "rows": []},
            )
            for sample_name, labels, value in fam["samples"]:
                labels = {
                    k: v for k, v in labels.items() if k != label
                }
                labels[label] = replica
                slot["rows"].append((sample_name, labels, value))
    lines: list[str] = []
    for name in sorted(merged):
        fam = merged[name]
        if fam["help"]:
            lines.append(f"# HELP {name} {fam['help']}")
        lines.append(f"# TYPE {name} {fam['kind']}")
        for sample_name, labels, value in fam["rows"]:
            lines.append(
                f"{sample_name}{_render_labels(labels)} {_fmt(value)}"
            )
    return "\n".join(lines) + "\n" if lines else ""


def merge_fleet_trace(
    router_trace: dict, replicas: list[dict]
) -> dict:
    """One clock-aligned Chrome trace from the router's own export
    plus each replica's (`[{"name", "trace", "offset_s"}]`, where
    `offset_s` is the replica clock MINUS the router clock — an
    in-process replica's is 0.0 by construction).

    Every source's events are re-based into the router clock frame
    (`t_router = clock_origin + ts/1e6 - offset_s`), given a distinct
    Chrome pid, and sorted — scrubbing the merged file in Perfetto
    shows one request's router route/queue spans and its engine's
    prefill/decode spans in true order under one trace id. Exact
    per-span metadata (the engine decode event's `ttft_s`, PR 3's
    record-equal floats) rides through untouched in event args, so
    the merge never degrades span-derived latencies to microsecond
    rounding. Sources with no clock origin (empty traces) are
    skipped and listed in `otherData.skipped`."""
    sources: list[tuple[str, int, dict | None, float]] = [
        ("router", 1, router_trace, 0.0)
    ]
    pid = 10
    for rep in replicas:
        sources.append((
            f"replica {rep['name']}", pid, rep.get("trace"),
            float(rep.get("offset_s") or 0.0),
        ))
        pid += 1
    staged: list[tuple[float, dict]] = []
    metas: list[dict] = []
    skipped: list[str] = []
    processes: dict[int, str] = {}
    for name, pid, trace, offset in sources:
        if not isinstance(trace, dict):
            if trace is not None:
                skipped.append(name)
            continue
        events = trace.get("traceEvents") or []
        origin = (trace.get("otherData") or {}).get(
            "clock_origin_monotonic_s"
        )
        if origin is None:
            if events:
                skipped.append(name)
            continue
        processes[pid] = name
        base = float(origin) - offset  # router-clock second of ts=0
        for event in events:
            event = dict(event)
            event["pid"] = pid
            if event.get("ph") == "M":
                if event.get("name") == "process_name":
                    continue  # replaced by the merged process metas
                metas.append(event)
                continue
            staged.append(
                (base + float(event.get("ts", 0)) / 1e6, event)
            )
    if staged:
        t0 = min(t for t, _ in staged)
    else:
        t0 = 0.0
    out: list[dict] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "args": {"name": name},
        }
        for pid, name in sorted(processes.items())
    ]
    out.extend(metas)
    rebased = []
    for abs_t, event in staged:
        event["ts"] = max(0, int(round((abs_t - t0) * 1e6)))
        rebased.append(event)
    rebased.sort(key=lambda e: e["ts"])
    out.extend(rebased)
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "clock_origin_monotonic_s": t0 if staged else None,
            "processes": {
                str(pid): name
                for pid, name in sorted(processes.items())
            },
            "skipped": skipped,
        },
    }

"""CanaryController: the digest-gated config-rollout verdict machine.

ROADMAP item 4b: config changes used to roll out blind — the fleet
could capture traffic and replay it offline (PR 15), but nothing
watched a CANDIDATE config serve real traffic and proved it correct
before promotion. The router's canary role fixes that: a
candidate-config replica receives a mirrored copy of a sampled
fraction of live submits (same prompt, knobs, and EFFECTIVE seed —
the PR 15 rid-defaulting rule pins the PRNG stream, so a sampled
mirror is as deterministic as a greedy one), the primary's response
serves the user, and this controller compares the two streams at the
completion seam.

The comparison is MATHEMATICAL where the configs allow it, and
statistical only where they don't:

- **Digest-exact gate**: `sim/replay.classify_config_delta` inspects
  the primary-vs-canary fingerprint delta up front. Every field
  within the token-preserving set (all `ENGINE_KNOBS` replay axes,
  `tp_devices`, dtype moves within {"model", "int8-sim"}) — or an
  empty delta — arms the gate: the candidate MUST produce
  byte-identical token streams, verified per request by crc32 token
  digest (truncated completions compare by common prefix, the PR 15
  rule: a truncation point is pool pressure, not the serving
  function). One divergence is a REJECT — no vote, no window —
  because a violated purity invariant never becomes acceptable with
  more samples. The divergence dumps a flight-recorder bundle in the
  replay-triage format: both fingerprints, the offending record, and
  expected/got at the first divergent token.
- **Latency windows** (always, and the only verdict input when a
  delta field moves the serving function — e.g. a real-int8
  candidate, where token drift is declared and expected): primary
  and mirror TTFT/TPOT land in per-side histograms read through
  `BucketRing` windows; the p99 deltas must stay within
  `latency_budget_pct` for the promote path and sustained regression
  past it rejects.

Verdicts are hysteretic — warming (until `min_compared` pairs) ->
observing -> promote after `promote_ticks` consecutive clean
evaluation ticks / reject on a digest divergence (immediate) or
`reject_ticks` consecutive breached ones. The router applies the
verdict: promote flips the canary to a full serving role and records
the winning fingerprint; reject drains it migrate-first with trace
reason `canary_reject`.

The controller is deliberately router-agnostic: `on_primary` /
`on_mirror` feed completion records, `evaluate(now)` advances the
machine, and the router (or a test scripting fakes) owns every side
effect. Metrics flow through the RouterObs bundle handed in
(`router_canary_*` catalog family); no literal metric names here.
"""

from __future__ import annotations

import time

from walkai_nos_tpu.obs.metrics import Histogram, Registry
from walkai_nos_tpu.obs.slo import BucketRing

__all__ = ["CanaryController"]

# Verdict-machine states, in lifecycle order.
STATES = ("warming", "observing", "promote", "reject")


def _tpot_s(record: dict) -> float | None:
    """Per-output-token latency of one completion record (the
    engine's record-derived TPOT): decode wall after the first token
    over the tokens it produced. None under two tokens — a
    single-token completion has no decode cadence."""
    tokens = record.get("tokens")
    ttft = record.get("ttft_s")
    wall = record.get("wall_s")
    if tokens is None or ttft is None or wall is None:
        return None
    n = len(tokens)
    if n < 2:
        return None
    return max(0.0, float(wall) - float(ttft)) / (n - 1)


class CanaryController:
    """Pairs primary/mirror completions, diffs streams, holds the
    verdict machine. One controller per canary replica."""

    def __init__(
        self,
        *,
        obs=None,
        trace=None,
        flight=None,
        canary_name: str = "canary",
        min_compared: int = 8,
        promote_ticks: int = 3,
        reject_ticks: int = 3,
        latency_budget_pct: float = 20.0,
        window_s: float = 30.0,
        buckets: int = 15,
    ):
        self.canary_name = canary_name
        self.min_compared = int(min_compared)
        self.promote_ticks = int(promote_ticks)
        self.reject_ticks = int(reject_ticks)
        self.latency_budget_pct = float(latency_budget_pct)
        self._obs = obs
        self._trace = trace
        self._flight = flight
        # Armed digest gate until fingerprints say otherwise: a canary
        # whose fingerprint never arrives (bare fakes) is held to the
        # exact standard — silence must not relax the gate.
        self.gate_armed = True
        self.delta: dict = {
            "delta": [], "token_preserving": True, "moving_fields": [],
        }
        self._fingerprints: dict = {"primary": None, "canary": None}
        self.state = "warming"
        self.mirrored = 0
        self.compared = 0
        self.divergences = 0
        self.mirror_errors = 0
        self._clean_ticks = 0
        self._breach_ticks = 0
        self.verdict_reason: str | None = None
        self.first_divergence: dict | None = None
        self.winning_fingerprint_id: str | None = None
        # rid -> {"primary": record, "mirror": record}; compared and
        # dropped once both sides land.
        self._pending: dict[int, dict] = {}
        self._latency_delta: dict[str, float | None] = {
            "ttft_p99": None, "tpot_p99": None,
        }
        # Per-side latency windows: own private registry (these
        # histograms are comparison scratch, not exported series —
        # the DELTA is the exported gauge).
        scratch = Registry(enabled=True)
        self._hists: dict[str, Histogram] = {}
        self._rings: dict[str, BucketRing] = {}
        for side in ("primary", "mirror"):
            for kind in ("ttft", "tpot"):
                key = f"{side}_{kind}"
                hist = scratch.histogram(
                    f"canary_{key}_s", "canary comparison scratch"
                )
                self._hists[key] = hist
                self._rings[key] = BucketRing(
                    hist, window_s=window_s, buckets=buckets
                )

    # -- configuration --------------------------------------------------

    def set_fingerprints(self, primary: dict | None, canary: dict | None):
        """Classify the config delta and set the gate. Either side
        None (a replica without the fingerprint surface) leaves the
        gate ARMED — the conservative default."""
        from walkai_nos_tpu.sim.replay import classify_config_delta

        self._fingerprints = {"primary": primary, "canary": canary}
        if primary is not None and canary is not None:
            self.delta = classify_config_delta(primary, canary)
            self.gate_armed = bool(self.delta["token_preserving"])

    # -- recording (router driver thread) -------------------------------

    def on_mirrored(self) -> None:
        """One live submit was mirrored to the canary."""
        self.mirrored += 1
        if self._obs is not None:
            self._obs.canary_mirrored.inc()

    def on_primary(self, rid: int, record: dict, now=None) -> None:
        self._observe("primary", record, now)
        slot = self._pending.setdefault(rid, {})
        slot["primary"] = record
        if "mirror" in slot:
            self._compare(rid, self._pending.pop(rid), now)

    def on_mirror(self, rid: int, record: dict, now=None) -> None:
        self._observe("mirror", record, now)
        slot = self._pending.setdefault(rid, {})
        slot["mirror"] = record
        if "primary" in slot:
            self._compare(rid, self._pending.pop(rid), now)

    def _observe(self, side: str, record: dict, now=None) -> None:
        now = time.monotonic() if now is None else now
        if record.get("error") is not None and side == "mirror":
            self.mirror_errors += 1
            if self._obs is not None:
                self._obs.canary_mirror_errors.inc()
        ttft = record.get("ttft_s")
        if ttft is not None:
            self._hists[f"{side}_ttft"].observe(float(ttft))
        tpot = _tpot_s(record)
        if tpot is not None:
            self._hists[f"{side}_tpot"].observe(tpot)
        for kind in ("ttft", "tpot"):
            self._rings[f"{side}_{kind}"].advance(now)

    # -- the diff -------------------------------------------------------

    def _compare(self, rid: int, pair: dict, now=None) -> None:
        primary, mirror = pair["primary"], pair["mirror"]
        self.compared += 1
        if mirror.get("error") is not None:
            # A mirror-side failure (canary rejected the submit, pod
            # error) is operational, not a token divergence: counted,
            # never promoted past.
            self._count_compare("mirror_error")
            return
        if not self.gate_armed:
            self._count_compare("latency_only")
            return
        p_tokens = primary.get("tokens")
        m_tokens = mirror.get("tokens")
        if p_tokens is None or m_tokens is None:
            self._count_compare("mirror_error")
            return
        expected = list(map(int, p_tokens))
        got = list(map(int, m_tokens))
        if primary.get("truncated") or mirror.get("truncated"):
            # PR 15 rule: a truncation point is pool pressure, not
            # the serving function — compare the common prefix.
            n = min(len(expected), len(got))
            match = expected[:n] == got[:n]
        else:
            match = expected == got
        if match:
            self._count_compare("match")
            return
        self._count_compare("divergent")
        self.divergences += 1
        if self._obs is not None:
            self._obs.canary_divergence.inc()
        self._record_divergence(rid, primary, mirror, expected, got, now)
        self._set_state(
            "reject",
            f"digest divergence on request {rid}",
            now,
        )

    def _count_compare(self, result: str) -> None:
        if self._obs is not None:
            self._obs.canary_compared.inc(labels={"result": result})

    def _record_divergence(
        self, rid, primary, mirror, expected, got, now=None
    ) -> None:
        from walkai_nos_tpu.sim.replay import first_divergence

        idx = first_divergence(expected, got)
        self.first_divergence = {
            "rid": rid,
            "trace_id": primary.get("trace_id"),
            "token_index": idx,
            "expected_token": (
                expected[idx] if idx < len(expected) else None
            ),
            "got_token": got[idx] if idx < len(got) else None,
        }
        if self._trace is not None:
            self._trace.event(
                "canary_divergence",
                time.monotonic() if now is None else now,
                rid=rid,
                canary=self.canary_name,
                token_index=idx,
            )
        if self._flight is not None:
            # The replay-triage bundle shape (PR 15): everything a
            # human needs to re-derive the verdict offline.
            bundle = {
                "verdict": dict(self.first_divergence),
                "canary": self.canary_name,
                "primary_fingerprint": self._fingerprints["primary"],
                "canary_fingerprint": self._fingerprints["canary"],
                "config_delta": dict(self.delta),
                "record": {
                    "rid": rid,
                    "trace_id": primary.get("trace_id"),
                    "primary_tokens": expected,
                    "mirror_tokens": got,
                    "primary_replica": primary.get("replica"),
                    "mirror_replica": mirror.get("replica"),
                },
            }
            path = self._flight.dump("canary_divergence", bundle)
            self.first_divergence["bundle_path"] = path
            if path is not None and self._obs is not None:
                self._obs.flight_dumps.inc(
                    labels={"trigger": "canary_divergence"}
                )

    # -- the verdict machine --------------------------------------------

    def _refresh_latency(self, now: float) -> dict[str, float | None]:
        """Windowed p99 deltas, percent over primary, None when
        either side's window is empty (no evidence either way)."""
        deltas: dict[str, float | None] = {}
        for kind in ("ttft", "tpot"):
            p = self._rings[f"primary_{kind}"].quantile(0.99, now)
            m = self._rings[f"mirror_{kind}"].quantile(0.99, now)
            if p is None or m is None or p <= 0:
                deltas[f"{kind}_p99"] = None
                continue
            pct = round(100.0 * (m - p) / p, 2)
            deltas[f"{kind}_p99"] = pct
            if self._obs is not None:
                self._obs.canary_latency_delta.set(
                    pct, labels={"metric": f"{kind}_p99"}
                )
        self._latency_delta = deltas
        return deltas

    def _set_state(self, state: str, reason: str, now=None) -> None:
        if self.state in ("promote", "reject"):
            return  # terminal verdicts are sticky
        prev = self.state
        self.state = state
        self.verdict_reason = reason
        if state == "promote":
            fp = self._fingerprints["canary"] or {}
            self.winning_fingerprint_id = fp.get("id")
        if self._obs is not None:
            for s in STATES:
                self._obs.canary_verdict.set(
                    1.0 if s == state else 0.0, labels={"state": s}
                )
        if self._trace is not None and prev != state:
            self._trace.event(
                "canary_verdict",
                time.monotonic() if now is None else now,
                canary=self.canary_name,
                state=state,
                reason=reason,
            )

    def evaluate(self, now=None) -> str:
        """One evaluation tick (the router's throttled fleet refresh
        cadence). Advances warming -> observing on sample count, then
        counts consecutive clean / breached ticks toward the
        promote / reject thresholds. Returns the current state."""
        now = time.monotonic() if now is None else now
        if self.state in ("promote", "reject"):
            return self.state
        if self._obs is not None and self.state == "warming":
            # Publish the warming state before the first transition
            # so the gauge family is never silent while a canary runs.
            self._obs.canary_verdict.set(
                1.0, labels={"state": "warming"}
            )
        deltas = self._refresh_latency(now)
        if self.compared < self.min_compared:
            return self.state
        if self.state == "warming":
            self._set_state("observing", "min_compared reached", now)
        measured = [v for v in deltas.values() if v is not None]
        breached = any(
            v > self.latency_budget_pct for v in measured
        )
        if breached:
            self._breach_ticks += 1
            self._clean_ticks = 0
        else:
            self._clean_ticks += 1
            self._breach_ticks = 0
        if self._breach_ticks >= self.reject_ticks:
            worst = max(measured)
            self._set_state(
                "reject",
                f"latency regression {worst:+.1f}% past "
                f"{self.latency_budget_pct:.0f}% budget for "
                f"{self._breach_ticks} ticks",
                now,
            )
        elif self._clean_ticks >= self.promote_ticks:
            self._set_state(
                "promote",
                f"{self.compared} compared, {self.divergences} "
                f"divergences, latency within budget for "
                f"{self._clean_ticks} ticks",
                now,
            )
        return self.state

    # -- reading --------------------------------------------------------

    def stats(self) -> dict:
        """The `/debug/canary` payload + `router.stats()["canary"]`
        block: gate, counters, verdict, latency deltas, and the first
        divergence (if any) with its flight-bundle path."""
        return {
            "canary": self.canary_name,
            "state": self.state,
            "gate": (
                "digest_exact" if self.gate_armed else "latency_only"
            ),
            "config_delta": {
                "token_preserving": self.delta["token_preserving"],
                "moving_fields": list(self.delta["moving_fields"]),
                "fields": [
                    f"{d['section']}.{d['field']}"
                    for d in self.delta["delta"]
                ],
            },
            "mirrored": self.mirrored,
            "compared": self.compared,
            "pending": len(self._pending),
            "divergences": self.divergences,
            "mirror_errors": self.mirror_errors,
            "latency_delta_pct": dict(self._latency_delta),
            "verdict_reason": self.verdict_reason,
            "first_divergence": (
                dict(self.first_divergence)
                if self.first_divergence is not None else None
            ),
            "winning_fingerprint": self.winning_fingerprint_id,
        }

"""Telemetry subsystem: metrics registry, request tracing, profiling.

- `obs.metrics` — counters/gauges/log-bucketed histograms + Prometheus
  text exposition; the ONE registry implementation every surface
  (serving engine, kube binaries, install exporter) shares.
- `obs.trace` — bounded event ring + per-request lifecycle spans with
  Chrome trace-event export.
- `obs.profile` — jax.profiler capture window gated on the dispatch
  loop.
- `obs.catalog` — declarative list of every exported metric
  (`hack/metrics_lint.py` holds it and docs/observability.md to each
  other).
- `obs.serving` — `ServingObs`, the bundle `models/serve.py` and the
  demo server consume.

See docs/observability.md for the exported-metric reference and the
trace/profile how-to.
"""

from walkai_nos_tpu.obs.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    Registry,
    log_buckets,
)
from walkai_nos_tpu.obs.profile import ProfileHook  # noqa: F401
from walkai_nos_tpu.obs.serving import ServingObs  # noqa: F401
from walkai_nos_tpu.obs.trace import RequestTrace, Ring  # noqa: F401

"""Telemetry subsystem: metrics registry, request tracing, profiling.

- `obs.metrics` — counters/gauges/log-bucketed histograms + Prometheus
  text exposition; the ONE registry implementation every surface
  (serving engine, kube binaries, install exporter) shares.
- `obs.trace` — bounded event ring + per-request lifecycle spans with
  Chrome trace-event export.
- `obs.profile` — jax.profiler capture window gated on the dispatch
  loop.
- `obs.attrib` — per-dispatch device-time attribution: host assembly
  vs blocked device sync, classified by dispatch composition, paired
  with the analytic HBM cost model for a live roofline fraction.
- `obs.slo` — sliding-window (ring-of-buckets) SLO views over the
  cumulative histograms, burn-rate gauges, and the composed
  `cb_saturation` scale signal.
- `obs.capture` — the deterministic capture plane: a bounded rotating
  on-disk recorder of request inputs + completion digests behind an
  engine config fingerprint, replayable token-identically by
  `sim/replay.py`.
- `obs.catalog` — declarative list of every exported metric
  (`hack/metrics_lint.py` holds it and docs/observability.md to each
  other).
- `obs.serving` — `ServingObs`, the bundle `models/serve.py` and the
  demo server consume.
- `obs.router` — `RouterObs`, the fleet router's bundle
  (`walkai_nos_tpu/router`, `cmd/serverouter.py`): the `router_*`
  series built from the same catalog.

See docs/observability.md for the exported-metric reference and the
trace/profile how-to.
"""

from walkai_nos_tpu.obs.anomaly import (  # noqa: F401
    AnomalyDetector,
    FlightRecorder,
)
from walkai_nos_tpu.obs.capture import (  # noqa: F401
    CaptureLog,
    fingerprint_id,
    token_digest,
    tree_crc32,
)
from walkai_nos_tpu.obs.attrib import (  # noqa: F401
    DispatchAttribution,
    classify_dispatch,
    kv_hbm_bytes_per_token,
    params_hbm_bytes,
)
from walkai_nos_tpu.obs.federation import (  # noqa: F401
    FEDERATED_PREFIXES,
    federate,
    merge_fleet_trace,
    parse_exposition,
)
from walkai_nos_tpu.obs.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    Registry,
    log_buckets,
)
from walkai_nos_tpu.obs.profile import ProfileHook  # noqa: F401
from walkai_nos_tpu.obs.router import RouterObs  # noqa: F401
from walkai_nos_tpu.obs.serving import ServingObs  # noqa: F401
from walkai_nos_tpu.obs.slo import BucketRing, SloTracker  # noqa: F401
from walkai_nos_tpu.obs.trace import (  # noqa: F401
    RequestTrace,
    Ring,
    RouterTrace,
)

"""ServingObs: the serving engine's telemetry bundle.

One object carrying the three obs legs the continuous batcher and the
demo server share:

- `registry` + one instrument attribute per `component="serving"`
  catalog spec (`obs.submitted.inc()`, `obs.ttft.observe(...)`, ...) —
  built from `obs/catalog.py`, so serve.py contains no literal metric
  names and `make metrics-lint` can hold the catalog and the docs to
  each other;
- `trace`: the request-lifecycle span recorder + event ring
  (`/debug/trace` serves its Chrome export);
- `profile`: the jax.profiler capture-window hook (armed by env or
  `/debug/profile`), ticked once per engine dispatch.

`enabled=False` builds the whole bundle in no-op mode: every write
short-circuits on one flag check, reads return zeros/None. That arm
exists to be MEASURED — `bench_lm.measure_obs_overhead` runs the same
workload with both bundles and reports `obs_overhead_pct`, gated < 2%
by `make bench-check`.
"""

from __future__ import annotations

from walkai_nos_tpu.obs.catalog import serving_specs
from walkai_nos_tpu.obs.metrics import Registry
from walkai_nos_tpu.obs.profile import ProfileHook
from walkai_nos_tpu.obs.trace import RequestTrace

__all__ = ["ServingObs", "bind_catalog_instruments"]


def bind_catalog_instruments(target, specs, registry: Registry) -> None:
    """Build one registry instrument per catalog spec and set it as an
    attribute on `target` (spec.attr). The ONE instruments-from-catalog
    path every obs bundle uses (`ServingObs`, `obs/router.RouterObs`):
    bundles contain no literal metric names, so a name that isn't in
    `obs/catalog.py` doesn't exist and `make metrics-lint` can hold the
    catalog and the docs to each other."""
    for spec in specs:
        if spec.kind == "counter":
            inst = registry.counter(spec.name, spec.help)
        elif spec.kind == "gauge":
            inst = registry.gauge(spec.name, spec.help)
        else:
            inst = registry.histogram(
                spec.name, spec.help, buckets=spec.buckets
            )
        setattr(target, spec.attr, inst)


class ServingObs:
    def __init__(
        self,
        *,
        enabled: bool = True,
        registry: Registry | None = None,
        trace_events: int = 4096,
        trace_requests: int = 1024,
        profile: ProfileHook | None = None,
    ):
        self.enabled = enabled
        self.registry = registry or Registry(enabled=enabled)
        self.trace = RequestTrace(
            capacity=trace_events,
            keep_done=trace_requests,
            enabled=enabled,
        )
        if profile is not None:
            self.profile = profile
        elif enabled:
            self.profile = ProfileHook.from_env()
        else:
            # The no-op bundle must be a REAL no-op: never let ambient
            # WALKAI_PROFILE_* env arm a capture on a
            # telemetry-disabled engine (or bias the overhead A/B's
            # disabled arm).
            self.profile = ProfileHook()
        bind_catalog_instruments(self, serving_specs(), self.registry)

    def render(self) -> str:
        return self.registry.render()

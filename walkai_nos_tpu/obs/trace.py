"""Request-lifecycle tracing: bounded event ring + per-request spans.

Two complementary views of the serving engine, both host-side and
bounded (a long-running server must never grow telemetry without
limit):

- **Event ring** (`Ring`): a fixed-capacity circular buffer of raw
  engine events (submits, admissions, prefill chunks, dispatches,
  errors). Wraparound overwrites the oldest entry; `snapshot()`
  returns survivors oldest-first. This is the "what just happened"
  flight recorder — cheap enough to leave on in production.
- **Lifecycle spans** (`RequestTrace`): per-request timelines
  (submit -> queued -> prefill chunk(s) -> first token -> decode ->
  done/error) keyed by request id, retained for the last
  `keep_done` finished requests. The span clock is the CALLER's
  timestamp, not a second `time.monotonic()` read: the engine passes
  the exact floats it stores on the request record, so
  `ttft_s`/`wall_s` reconstructed here equal `drain_done_records()`
  values EXACTLY (pinned by tests/test_obs.py) — the trace is the
  same truth, not a parallel approximation.

`chrome_trace()` exports both as Chrome trace-event JSON (the
`chrome://tracing` / Perfetto format: one process, one track per
request, duration events for the queued/prefill/decode phases,
instant events for chunks and ring entries) — load the
`/debug/trace` payload straight into a trace viewer.
"""

from __future__ import annotations

import re
import threading
from collections import OrderedDict, deque

__all__ = ["Ring", "RequestTrace", "RouterTrace", "valid_trace_id"]

# The cross-process trace-id charset contract, in ONE place: the
# router (adopting a client's X-Walkai-Trace) and the demo server
# (adopting the router's) must agree EXACTLY, or an id minted on one
# side gets rejected and re-minted on the other and the correlation
# silently breaks. An id is a label in traces, headers, and JSON —
# it must never carry arbitrary bytes.
_TRACE_ID = re.compile(r"[A-Za-z0-9._:-]{1,64}")


def valid_trace_id(value) -> str | None:
    """`value` when it is a well-formed trace id, else None (caller
    mints its own)."""
    if isinstance(value, str) and _TRACE_ID.fullmatch(value):
        return value
    return None

# Lifecycle phase names (span event keys, also the Chrome track names).
SUBMIT = "submit"
ADMITTED = "admitted"
PREFILL_CHUNK = "prefill_chunk"
FIRST_TOKEN = "first_token"
DONE = "done"
ERROR = "error"
# Speculative-serving round phases (engine-level ring events — the
# draft scan and the target verify run fused in one device dispatch,
# so the phases are markers at the round's host sync, not separately
# timed sub-spans).
SPEC_DRAFT = "spec_draft"
SPEC_VERIFY = "spec_verify"
# Per-dispatch attribution event: host-assembly vs blocked-device-sync
# durations, recorded at the dispatch's host sync (obs/attrib.py is
# the registry side; this is the trace side, rendered as duration
# events on the engine track so /debug/trace shows device vs host
# time per dispatch).
DISPATCH = "dispatch"


class Ring:
    """Fixed-capacity circular buffer. Appends are O(1); once full,
    each append overwrites the oldest entry (`dropped` counts how
    many were lost). `snapshot()` returns entries oldest-first."""

    def __init__(self, capacity: int):
        if capacity <= 0:
            raise ValueError(f"capacity must be > 0; got {capacity}")
        self.capacity = capacity
        self._buf: list = [None] * capacity
        self._next = 0  # next write position
        self._count = 0  # lifetime appends
        self._lock = threading.Lock()

    def append(self, item) -> None:
        with self._lock:
            self._buf[self._next] = item
            self._next = (self._next + 1) % self.capacity
            self._count += 1

    @property
    def dropped(self) -> int:
        with self._lock:
            return max(0, self._count - self.capacity)

    def __len__(self) -> int:
        with self._lock:
            return min(self._count, self.capacity)

    def snapshot(self) -> list:
        with self._lock:
            if self._count <= self.capacity:
                return [x for x in self._buf[: self._count]]
            # Full: oldest sits at the write cursor.
            return self._buf[self._next:] + self._buf[: self._next]


class RequestTrace:
    """Per-request lifecycle spans + the raw event ring.

    All record methods take the event time `t` (the engine's
    `time.monotonic()` read) explicitly — see the module docstring
    for why. Completed spans are retained newest-last up to
    `keep_done`; live spans are never evicted (their count is bounded
    by the engine's slots + queue)."""

    def __init__(
        self,
        capacity: int = 4096,
        keep_done: int = 1024,
        enabled: bool = True,
    ):
        self.enabled = enabled
        self.ring = Ring(capacity)
        self._keep_done = keep_done
        self._lock = threading.Lock()
        self._spans: "OrderedDict[int, dict]" = OrderedDict()
        self._done_rids: deque[int] = deque()

    # -- recording -----------------------------------------------------

    def event(self, name: str, t: float, rid=None, **args) -> None:
        """Raw ring event (no span bookkeeping)."""
        if not self.enabled:
            return
        ev = {"name": name, "t": t}
        if rid is not None:
            ev["rid"] = rid
        if args:
            ev["args"] = args
        self.ring.append(ev)

    def submit(
        self, rid: int, t: float, prompt_len: int, max_new: int,
        trace_id: str | None = None,
    ) -> None:
        """`trace_id` is the cross-process correlation id minted by
        whatever front-end routed the request here (the fleet
        router's `X-Walkai-Trace`); the span carries it so the
        engine's lifecycle events and the router's route/queue spans
        merge under one id in the fleet `/debug/trace`."""
        if not self.enabled:
            return
        with self._lock:
            span = {
                "rid": rid,
                SUBMIT: t,
                "prompt_len": prompt_len,
                "max_new": max_new,
                "chunks": [],
            }
            if trace_id is not None:
                span["trace_id"] = trace_id
            self._spans[rid] = span
        self.event(
            SUBMIT, t, rid=rid, prompt_len=prompt_len, max_new=max_new
        )

    def admitted(
        self, rid: int, t: float, slot: int, blocks: int,
        cached: int = 0,
    ) -> None:
        """`cached` = prompt tokens served from the shared prefix
        cache at admission (0 when the cache is off or cold)."""
        if not self.enabled:
            return
        with self._lock:
            span = self._spans.get(rid)
            if span is not None:
                span[ADMITTED] = t
                span["slot"] = slot
                span["blocks"] = blocks
                span["cached"] = cached
        self.event(
            ADMITTED, t, rid=rid, slot=slot, blocks=blocks,
            cached=cached,
        )

    def prefill_chunk(
        self, rid: int, t: float, consumed: int, total: int
    ) -> None:
        if not self.enabled:
            return
        with self._lock:
            span = self._spans.get(rid)
            if span is not None:
                span["chunks"].append((t, consumed))
        self.event(
            PREFILL_CHUNK, t, rid=rid, consumed=consumed, total=total
        )

    def spec_round(
        self, t: float, k: int, live_slots: int, accepted: int
    ) -> None:
        """One speculative draft-and-verify round: a draft-phase and a
        verify-phase marker on the engine track (tid 0 in the Chrome
        export). `accepted` is the round's total accepted draft
        tokens across the `live_slots` slots that carried a request —
        the per-round acceptance story a trace viewer can scrub."""
        if not self.enabled:
            return
        self.event(SPEC_DRAFT, t, k=k, live_slots=live_slots)
        self.event(
            SPEC_VERIFY, t, k=k, live_slots=live_slots,
            accepted=accepted,
        )

    def dispatch(
        self, t_sync: float, kind: str, steps: int,
        host_s: float, device_s: float,
    ) -> None:
        """One dispatch's attribution: `t_sync` is its host sync (the
        engine clock read every record in the chunk shares), `kind`
        its composition class (obs/attrib.py), `host_s` the measured
        assembly time and `device_s` the blocked device sync that
        ended at `t_sync`. The Chrome export renders these as
        back-to-back duration events on the engine track."""
        if not self.enabled:
            return
        self.event(
            DISPATCH, t_sync, kind=kind, steps=steps,
            host_ms=round(host_s * 1e3, 3),
            device_ms=round(device_s * 1e3, 3),
        )

    def first_token(self, rid: int, t: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            span = self._spans.get(rid)
            if span is not None and FIRST_TOKEN not in span:
                span[FIRST_TOKEN] = t

    def _finish_locked(self, span: dict, t: float, reason: str) -> None:
        """Close a span and evict beyond the retention bound — the ONE
        retention rule both terminal paths share. Caller holds the
        lock."""
        span[DONE] = t
        span["reason"] = reason
        self._done_rids.append(span["rid"])
        while len(self._done_rids) > self._keep_done:
            self._spans.pop(self._done_rids.popleft(), None)

    def done(
        self, rid: int, t: float, reason: str, n_tokens: int
    ) -> None:
        if not self.enabled:
            return
        with self._lock:
            span = self._spans.get(rid)
            if span is not None:
                span["n_tokens"] = n_tokens
                self._finish_locked(span, t, reason)
        self.event(DONE, t, rid=rid, reason=reason, n_tokens=n_tokens)

    def error(self, t: float, reason: str, rid=None, **args) -> None:
        """Errors may predate a request id (submit-time rejects)."""
        if not self.enabled:
            return
        if rid is not None:
            with self._lock:
                span = self._spans.get(rid)
                if span is not None:
                    self._finish_locked(span, t, f"error:{reason}")
        self.event(ERROR, t, rid=rid, reason=reason, **args)

    # -- reading -------------------------------------------------------

    def timeline(self, rid: int) -> dict | None:
        with self._lock:
            span = self._spans.get(rid)
            if span is None:
                return None
            out = dict(span)
            out["chunks"] = list(span["chunks"])
            return out

    def ttft_s(self, rid: int) -> float | None:
        """submit -> first token, from the span clock — equals the
        engine's `drain_done_records()["ttft_s"]` exactly."""
        with self._lock:
            span = self._spans.get(rid)
            if span is None or FIRST_TOKEN not in span:
                return None
            return span[FIRST_TOKEN] - span[SUBMIT]

    def wall_s(self, rid: int) -> float | None:
        with self._lock:
            span = self._spans.get(rid)
            if span is None or DONE not in span:
                return None
            return span[DONE] - span[SUBMIT]

    def spans(self) -> list[dict]:
        with self._lock:
            return [
                {**s, "chunks": list(s["chunks"])}
                for s in self._spans.values()
            ]

    # -- export --------------------------------------------------------

    def chrome_trace(self) -> dict:
        """Chrome trace-event JSON (`chrome://tracing` / Perfetto).

        One process ("cb-engine"), one track (tid) per request id.
        Phases become duration events ("ph": "X"): queued
        (submit -> admitted, or -> first token when admission isn't
        traced), prefill (admitted -> first token), decode
        (first token -> done). Prefill chunks and raw ring events are
        instants ("ph": "i"). Timestamps are microseconds relative to
        the earliest event, per the format; that origin is exported
        as `otherData.clock_origin_monotonic_s` so the fleet merger
        (`obs/federation.merge_fleet_trace`) can re-base this
        process's events onto the router's clock. Span args carry the
        trace id (when the submit had one) plus the EXACT span-clock
        `ttft_s`/`wall_s` floats, so the merged timeline never
        degrades the PR 3 record-equality guarantee to microsecond
        rounding."""
        spans = self.spans()
        events = self.ring.snapshot()
        times = [s[SUBMIT] for s in spans] + [e["t"] for e in events]
        if not times:
            return {
                "traceEvents": [],
                "displayTimeUnit": "ms",
                "otherData": {"clock_origin_monotonic_s": None},
            }
        t0 = min(times)

        def us(t: float) -> int:
            return int(round((t - t0) * 1e6))

        out = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": 1,
                "args": {"name": "cb-engine"},
            }
        ]
        for s in spans:
            rid = s["rid"]
            meta = {
                "name": "thread_name",
                "ph": "M",
                "pid": 1,
                "tid": rid,
                "args": {"name": f"request {rid}"},
            }
            out.append(meta)
            submit = s[SUBMIT]
            admitted = s.get(ADMITTED)
            first = s.get(FIRST_TOKEN)
            done = s.get(DONE)
            trace_id = s.get("trace_id")
            id_args = (
                {} if trace_id is None else {"trace_id": trace_id}
            )
            queued_end = admitted or first or done
            if queued_end is not None:
                out.append({
                    "name": "queued",
                    "ph": "X",
                    "pid": 1,
                    "tid": rid,
                    "ts": us(submit),
                    "dur": max(0, us(queued_end) - us(submit)),
                    "args": {
                        "prompt_len": s.get("prompt_len"),
                        "max_new": s.get("max_new"),
                        **id_args,
                    },
                })
            if admitted is not None and first is not None:
                out.append({
                    "name": "prefill",
                    "ph": "X",
                    "pid": 1,
                    "tid": rid,
                    "ts": us(admitted),
                    "dur": max(0, us(first) - us(admitted)),
                    "args": {
                        "slot": s.get("slot"),
                        "blocks": s.get("blocks"),
                        "cached": s.get("cached"),
                        "chunks": len(s["chunks"]),
                        **id_args,
                    },
                })
            for t, consumed in s["chunks"]:
                out.append({
                    "name": "prefill_chunk",
                    "ph": "i",
                    "s": "t",
                    "pid": 1,
                    "tid": rid,
                    "ts": us(t),
                    "args": {"consumed": consumed},
                })
            if first is not None and done is not None:
                out.append({
                    "name": "decode",
                    "ph": "X",
                    "pid": 1,
                    "tid": rid,
                    "ts": us(first),
                    "dur": max(0, us(done) - us(first)),
                    "args": {
                        "reason": s.get("reason"),
                        "n_tokens": s.get("n_tokens"),
                        # Exact span-clock floats (== the request
                        # record's, PR 3), rounding-proof through the
                        # fleet merge.
                        "ttft_s": first - submit,
                        "wall_s": done - submit,
                        **id_args,
                    },
                })
        engine_track_named = False
        for e in events:
            if e["name"] in (SUBMIT, ADMITTED, PREFILL_CHUNK, DONE):
                continue  # already represented as span structure
            if e["name"] == DISPATCH:
                # Device-vs-host attribution phases on the engine
                # track (tid 0): the blocked device sync ended at the
                # event time, the host assembly directly preceded the
                # dispatch. Rendered back to back ending at the sync —
                # under pipelining the host work actually overlapped
                # the previous chunk's device time, so the layout is
                # the attribution, not a wall-clock gantt.
                if not engine_track_named:
                    engine_track_named = True
                    out.append({
                        "name": "thread_name",
                        "ph": "M",
                        "pid": 1,
                        "tid": 0,
                        "args": {"name": "engine dispatches"},
                    })
                args = e.get("args", {})
                device_s = args.get("device_ms", 0.0) / 1e3
                host_s = args.get("host_ms", 0.0) / 1e3
                kind = args.get("kind", "?")
                out.append({
                    "name": f"host:{kind}",
                    "ph": "X",
                    "pid": 1,
                    "tid": 0,
                    "ts": max(0, us(e["t"] - device_s - host_s)),
                    "dur": max(0, us(e["t"] - device_s))
                    - max(0, us(e["t"] - device_s - host_s)),
                    "args": args,
                })
                out.append({
                    "name": f"device:{kind}",
                    "ph": "X",
                    "pid": 1,
                    "tid": 0,
                    "ts": max(0, us(e["t"] - device_s)),
                    "dur": us(e["t"]) - max(0, us(e["t"] - device_s)),
                    "args": args,
                })
                continue
            out.append({
                "name": e["name"],
                "ph": "i",
                "s": "g",
                "pid": 1,
                "tid": e.get("rid", 0),
                "ts": us(e["t"]),
                "args": e.get("args", {}),
            })
        return {
            "traceEvents": out,
            "displayTimeUnit": "ms",
            "otherData": {
                "dropped_ring_events": self.ring.dropped,
                "clock_origin_monotonic_s": t0,
            },
        }


class RouterTrace:
    """The fleet router's side of a request's cross-process timeline:
    per-request route/queue/round-trip spans plus a bounded event ring
    the reconciler's scale events and the anomaly detector's flips
    land on — so `/debug/trace` shows autoscaler actions on the same
    timeline as the traffic that caused them.

    Mirrors `RequestTrace`'s conventions exactly: the caller passes
    every timestamp (the router's own `time.monotonic()` reads, so
    span math equals the router's bookkeeping), completed spans are
    retained newest-last up to `keep_done`, and `chrome_trace()`
    exports with the clock origin the fleet merger needs."""

    def __init__(
        self,
        capacity: int = 4096,
        keep_done: int = 1024,
        enabled: bool = True,
    ):
        self.enabled = enabled
        self.ring = Ring(capacity)
        self._keep_done = keep_done
        self._lock = threading.Lock()
        self._spans: "OrderedDict[int, dict]" = OrderedDict()
        self._done_rids: deque[int] = deque()

    def event(self, name: str, t: float, rid=None, **args) -> None:
        """Raw ring event (scale_up / drain_start / release /
        anomaly_flagged / flight_dump ... — the fleet-plane flight
        recorder's recent-history feed)."""
        if not self.enabled:
            return
        ev = {"name": name, "t": t}
        if rid is not None:
            ev["rid"] = rid
        if args:
            ev["args"] = args
        self.ring.append(ev)

    def submit(
        self,
        rid: int,
        *,
        trace_id: str,
        t_submit: float,
        t_routed: float,
        replica: str,
        policy: str,
        t_enqueue: float | None = None,
        affinity_key: int | None = None,
    ) -> None:
        """One routed request: `t_enqueue` (when the front-end queued
        it, None for direct submits) -> `t_submit` (the router picked
        it up) -> `t_routed` (the replica accepted it)."""
        if not self.enabled:
            return
        with self._lock:
            self._spans[rid] = {
                "rid": rid,
                "trace_id": trace_id,
                "enqueue": t_enqueue,
                "submit": t_submit,
                "routed": t_routed,
                "replica": replica,
                "policy": policy,
                "affinity_key": affinity_key,
            }
        self.event(
            "route", t_routed, rid=rid, trace_id=trace_id,
            replica=replica, policy=policy,
        )

    def collected(self, rid: int, t: float) -> None:
        """The replica's finished record reached the router — closes
        the round-trip span."""
        if not self.enabled:
            return
        with self._lock:
            span = self._spans.get(rid)
            if span is None or "collected" in span:
                return
            span["collected"] = t
            self._done_rids.append(rid)
            while len(self._done_rids) > self._keep_done:
                self._spans.pop(self._done_rids.popleft(), None)

    def spans(self) -> list[dict]:
        with self._lock:
            return [dict(s) for s in self._spans.values()]

    def chrome_trace(self) -> dict:
        """Chrome trace-event JSON of the router process: one track
        per router rid (queue wait -> route decision -> replica
        round-trip duration events, each carrying the trace id and
        chosen replica in args), plus ring events as instants on a
        tid-0 "fleet events" track. Same clock-origin contract as
        `RequestTrace.chrome_trace`."""
        spans = self.spans()
        events = self.ring.snapshot()
        times = [s["submit"] for s in spans] + [
            e["t"] for e in events
        ]
        times += [
            s["enqueue"] for s in spans if s.get("enqueue") is not None
        ]
        if not times:
            return {
                "traceEvents": [],
                "displayTimeUnit": "ms",
                "otherData": {"clock_origin_monotonic_s": None},
            }
        t0 = min(times)

        def us(t: float) -> int:
            return int(round((t - t0) * 1e6))

        out: list[dict] = [{
            "name": "process_name",
            "ph": "M",
            "pid": 1,
            "args": {"name": "router"},
        }]
        for s in spans:
            rid = s["rid"]
            args = {
                "trace_id": s["trace_id"],
                "replica": s["replica"],
                "policy": s["policy"],
            }
            if s.get("affinity_key") is not None:
                args["affinity_key"] = f"{s['affinity_key']:08x}"
            out.append({
                "name": "thread_name",
                "ph": "M",
                "pid": 1,
                "tid": rid,
                "args": {"name": f"request {rid}"},
            })
            enqueue = s.get("enqueue")
            if enqueue is not None:
                out.append({
                    "name": "queue_wait",
                    "ph": "X",
                    "pid": 1,
                    "tid": rid,
                    "ts": us(enqueue),
                    "dur": max(0, us(s["submit"]) - us(enqueue)),
                    "args": args,
                })
            out.append({
                "name": "route",
                "ph": "X",
                "pid": 1,
                "tid": rid,
                "ts": us(s["submit"]),
                "dur": max(0, us(s["routed"]) - us(s["submit"])),
                "args": args,
            })
            collected = s.get("collected")
            if collected is not None:
                out.append({
                    "name": "replica_roundtrip",
                    "ph": "X",
                    "pid": 1,
                    "tid": rid,
                    "ts": us(s["routed"]),
                    "dur": max(0, us(collected) - us(s["routed"])),
                    "args": args,
                })
        if events:
            out.append({
                "name": "thread_name",
                "ph": "M",
                "pid": 1,
                "tid": 0,
                "args": {"name": "fleet events"},
            })
        for e in events:
            if e["name"] == "route":
                continue  # already represented as span structure
            out.append({
                "name": e["name"],
                "ph": "i",
                "s": "g",
                "pid": 1,
                "tid": 0,
                "ts": us(e["t"]),
                "args": e.get("args", {}),
            })
        return {
            "traceEvents": out,
            "displayTimeUnit": "ms",
            "otherData": {
                "dropped_ring_events": self.ring.dropped,
                "clock_origin_monotonic_s": t0,
            },
        }

"""Optional `jax.profiler` capture window, gated on the dispatch loop.

A device-level profile (XLA traces, TensorBoard-viewable) of exactly N
serving dispatches: arm the hook (by env at process start, or live via
the demo server's `/debug/profile` endpoint), and the engine's next
dispatch starts `jax.profiler.start_trace(logdir)`; after `n`
dispatches the trace stops and the capture lands in `logdir`
(inspect with `tensorboard --logdir` or xprof).

Everything is fail-safe: a missing/broken jax.profiler records the
error in `status()` and disarms instead of taking the serving loop
down — profiling is a diagnostic, never a liveness risk. The
unarmed-path cost is one attribute check per dispatch.

Env knobs (read by `ProfileHook.from_env`, i.e. at engine start):
- WALKAI_PROFILE_DIR: capture directory; arming requires it.
- WALKAI_PROFILE_DISPATCHES: window length in dispatches (default 20).
"""

from __future__ import annotations

import os
import threading

__all__ = ["ProfileHook"]


class ProfileHook:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._logdir: str | None = None
        self._remaining = 0
        self._active = False
        self._completed = 0  # capture windows finished
        self._last_error: str | None = None

    @classmethod
    def from_env(cls, env=os.environ) -> "ProfileHook":
        hook = cls()
        logdir = env.get("WALKAI_PROFILE_DIR")
        if logdir:
            try:
                n = int(env.get("WALKAI_PROFILE_DISPATCHES", "20"))
            except ValueError:
                n = 20
            hook.arm(n, logdir)
        return hook

    def arm(self, dispatches: int, logdir: str) -> None:
        """Schedule a capture of the next `dispatches` dispatches.
        Re-arming while a window is active is rejected (the running
        window finishes first)."""
        if dispatches <= 0:
            raise ValueError(
                f"dispatches must be > 0; got {dispatches}"
            )
        if not logdir:
            raise ValueError("logdir required")
        with self._lock:
            if self._active:
                raise RuntimeError("capture window already active")
            self._logdir = logdir
            self._remaining = int(dispatches)

    def on_dispatch(self) -> None:
        """Engine hook, called once per dispatch. Fast path (unarmed):
        one lock-free attribute check."""
        if self._remaining == 0 and not self._active:
            return
        with self._lock:
            if self._remaining > 0 and not self._active:
                if self._start(self._logdir):
                    self._active = True
                else:
                    self._remaining = 0  # disarm on failure
                    return
            if self._active:
                self._remaining -= 1
                if self._remaining <= 0:
                    self._stop()
                    self._active = False
                    self._completed += 1

    def _start(self, logdir: str) -> bool:
        try:
            import jax

            jax.profiler.start_trace(logdir)
            return True
        except Exception as e:  # noqa: BLE001 — diagnostics must not kill serving
            self._last_error = f"start_trace: {e!r}"
            return False

    def _stop(self) -> None:
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001
            self._last_error = f"stop_trace: {e!r}"

    def status(self) -> dict:
        with self._lock:
            return {
                "active": self._active,
                "remaining_dispatches": self._remaining,
                "logdir": self._logdir,
                "completed_windows": self._completed,
                "last_error": self._last_error,
            }

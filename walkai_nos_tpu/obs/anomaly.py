"""Straggler detection + flight recorder for the router fleet.

A single degraded replica (a throttled pod, a sick TP shard, a noisy
co-tenant) shows up at the fleet level only as a mysterious p99 bump —
every fleet-mean signal dilutes it by N. This module scores each
replica AGAINST THE REST OF THE FLEET instead:

- **`AnomalyDetector`** — for each windowed signal a replica already
  exports (dispatch p99 from the SLO window, `cb_device_step_ms`,
  `cb_device_roofline_fraction` — see `SIGNALS`), the
  replica's value is compared to the MEDIAN OF ITS PEERS
  (leave-one-out, so a 2-replica fleet still separates the straggler
  from the healthy baseline — a plain fleet-median would put the
  midpoint between them and normalize the deviation away). The
  deviation in the signal's own scale unit (relative to the peer
  median for latencies, absolute for bounded fractions — see the
  `SIGNALS` table) is a z-like score; the worst signal wins, and an
  EWMA smooths it so one
  noisy window neither flags nor clears anything. Flagging is
  hysteretic (flag at `threshold`, clear at `clear`), the same
  one-noisy-tick discipline as the autoscaler. The router exports the
  score as `router_replica_anomaly_score{replica}` and the flag as
  `router_replica_anomaly{replica}`, feeds the score into routing as
  a load penalty, and hands the flag to the reconciler as a
  drain-victim hint.
- **`FlightRecorder`** — a bounded on-disk ring of JSON bundles. When
  an anomaly flips or a replica's windowed SLO breaches, the router
  dumps what an operator needs to debug it AFTER the fact (the
  engine's `debug_state`, the recent router trace ring, the fleet's
  window quantiles) — the state is gone by the time a human looks,
  so it must be captured at the flip. Bounded both ways: at most
  `keep` bundles on disk (oldest pruned), at most one dump per
  `min_interval_s` (a flapping replica must not turn the recorder
  into a disk-filling loop). `cmd/serverouter.py` serves the ring at
  `/debug/flight`.

Stdlib-only, like every obs module the lint imports.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import time

__all__ = ["AnomalyDetector", "FlightRecorder", "SIGNALS"]

# signal key -> (direction, rel_scale, abs_scale).
#
# direction: +1.0 = higher-is-worse (latencies), -1.0 =
# lower-is-worse (roofline fraction: a degraded shard runs FURTHER
# from its memory roofline, not closer). The deviation unit is
# max(rel_scale x |peer median|, abs_scale): latencies scale
# RELATIVE to the fleet (a straggler is "2.5x its peers", whatever
# the absolute pace), while the [0, 1]-bounded roofline fraction
# needs an ABSOLUTE unit — a bounded signal can never sit multiple
# relative units below its median, so a relative scale could never
# flag it.
SIGNALS: dict[str, tuple[float, float, float]] = {
    "dispatch_p99_s": (1.0, 0.5, 0.0),
    "device_step_ms": (1.0, 0.5, 0.0),
    "roofline_fraction": (-1.0, 0.0, 0.15),
}

# Raw per-tick scores are clamped here before the EWMA: a zero-ish
# peer median would otherwise make one wild sample arbitrarily large
# and the EWMA's memory meaningless. The bound is deliberately low
# enough that ONE tick can never carry the default EWMA (alpha 0.3)
# past the default flag threshold (0.3 x 6 = 1.8 < 3) — flagging a
# straggler takes sustained deviation, never a single noisy window.
_CLAMP = 6.0


def _median(values: list[float]) -> float:
    ordered = sorted(values)
    n = len(ordered)
    mid = n // 2
    if n % 2:
        return ordered[mid]
    return (ordered[mid - 1] + ordered[mid]) / 2.0


class AnomalyDetector:
    """EWMA z-score of each replica's windowed signals against the
    peer median. Deterministic and jax-free: a scripted straggler
    trace through fakes exercises it exactly as production load
    does."""

    def __init__(
        self,
        *,
        threshold: float = 3.0,
        clear: float | None = None,
        alpha: float = 0.3,
        signals: dict[str, tuple[float, float, float]] | None = None,
    ):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1]; got {alpha}")
        if threshold <= 0:
            raise ValueError(
                f"threshold must be > 0; got {threshold}"
            )
        self.threshold = threshold
        self.clear = threshold / 2.0 if clear is None else clear
        if self.clear >= threshold:
            raise ValueError(
                f"clear ({self.clear}) must sit below threshold "
                f"({threshold}) for hysteresis"
            )
        self.alpha = alpha
        self.signals = dict(signals or SIGNALS)
        self._score: dict[str, float] = {}
        self._flag: dict[str, bool] = {}

    def score(self, name: str) -> float:
        return self._score.get(name, 0.0)

    def flagged(self, name: str) -> bool:
        return self._flag.get(name, False)

    def forget(self, name: str) -> None:
        """Drop a retired replica's state (its score must not haunt a
        future replica that reuses the name)."""
        self._score.pop(name, None)
        self._flag.pop(name, None)

    def update(
        self, fleet_signals: dict[str, dict | None]
    ) -> dict[str, dict]:
        """One scoring tick over `{replica: {signal: value|None}}`.
        Returns `{replica: {"score", "flagged", "signals"}}` where
        `signals` holds the per-signal raw deviations that fed the
        worst-signal score (the flight bundle's evidence). A signal
        fewer than two replicas report contributes nothing — a
        1-replica fleet has no peers to be a straggler of."""
        per_signal: dict[str, dict[str, float]] = {}
        for sig, (direction, rel, floor) in self.signals.items():
            values = {}
            for name, sigs in fleet_signals.items():
                v = (sigs or {}).get(sig)
                if isinstance(v, (int, float)) and v == v:
                    values[name] = float(v)
            if len(values) < 2:
                continue
            for name, x in values.items():
                peers = [
                    v for other, v in values.items() if other != name
                ]
                med = _median(peers)
                scale = max(rel * abs(med), floor, 1e-12)
                z = direction * (x - med) / scale
                per_signal.setdefault(name, {})[sig] = round(
                    max(-_CLAMP, min(_CLAMP, z)), 4
                )
        out: dict[str, dict] = {}
        for name in fleet_signals:
            deviations = per_signal.get(name, {})
            raw = max(deviations.values()) if deviations else 0.0
            prev = self._score.get(name, 0.0)
            score = prev + self.alpha * (raw - prev)
            self._score[name] = score
            flagged = self._flag.get(name, False)
            if not flagged and score >= self.threshold:
                flagged = True
            elif flagged and score <= self.clear:
                flagged = False
            self._flag[name] = flagged
            out[name] = {
                "score": round(score, 4),
                "flagged": flagged,
                "signals": deviations,
            }
        for name in list(self._score):
            if name not in fleet_signals:
                self.forget(name)
        return out


_SAFE = re.compile(r"[^A-Za-z0-9._-]+")


class FlightRecorder:
    """Bounded on-disk ring of JSON flight bundles."""

    def __init__(
        self,
        directory: str | None = None,
        *,
        keep: int = 8,
        min_interval_s: float = 5.0,
    ):
        if keep <= 0:
            raise ValueError(f"keep must be > 0; got {keep}")
        self.dir = directory or os.environ.get(
            "WALKAI_FLIGHT_DIR"
        ) or os.path.join(
            tempfile.gettempdir(), f"walkai-flight-{os.getpid()}"
        )
        self.keep = keep
        self.min_interval_s = min_interval_s
        self._last_at: float | None = None
        self._seq = self._max_existing_seq() + 1

    def _files(self) -> list[str]:
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        return sorted(
            n for n in names
            if n.startswith("flight-") and n.endswith(".json")
        )

    def _max_existing_seq(self) -> int:
        best = 0
        for name in self._files():
            m = re.match(r"flight-(\d+)-", name)
            if m:
                best = max(best, int(m.group(1)))
        return best

    def dump(
        self, trigger: str, payload: dict, *, now: float | None = None
    ) -> str | None:
        """Write one bundle; returns its path, or None when throttled
        (inside `min_interval_s` of the last dump) or the write
        failed — the recorder is telemetry and must never take the
        router down."""
        now = time.monotonic() if now is None else now
        if (
            self._last_at is not None
            and now - self._last_at < self.min_interval_s
        ):
            return None
        name = (
            f"flight-{self._seq:06d}-"
            f"{_SAFE.sub('_', trigger)[:32] or 'event'}.json"
        )
        path = os.path.join(self.dir, name)
        try:
            os.makedirs(self.dir, exist_ok=True)
            with open(path, "w") as f:
                json.dump(
                    {"trigger": trigger, **payload}, f, default=str
                )
        except (OSError, TypeError, ValueError):
            return None
        self._seq += 1
        self._last_at = now
        files = self._files()
        while len(files) > self.keep:
            try:
                os.remove(os.path.join(self.dir, files.pop(0)))
            except OSError:
                break
        return path

    def bundles(self) -> list[dict]:
        """Every retained bundle, oldest first, each with its file
        name under `_file`. Unreadable files are skipped (a crash
        mid-write must not break the endpoint)."""
        out = []
        for name in self._files():
            try:
                with open(os.path.join(self.dir, name)) as f:
                    bundle = json.load(f)
            except (OSError, ValueError):
                continue
            bundle["_file"] = name
            out.append(bundle)
        return out

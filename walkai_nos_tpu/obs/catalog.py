"""The metric catalog: every metric this repo exports, declared once.

Single source of truth three consumers share:

- `obs/serving.py` builds the serving engine's instruments from the
  `component="serving"` specs (no literal metric names in serve.py —
  a name that isn't here doesn't exist);
- `docs/observability.md` documents every row, and
  `hack/metrics_lint.py` (the `make metrics-lint` / tier-1 gate)
  asserts catalog and docs agree in BOTH directions, so a metric can
  be neither added nor renamed silently;
- the kube-side registrations (`kube/runtime.py` reconcile counters,
  `cmd/metricsexporter.py` install gauges) are declared here too: the
  lint scans the tree for literal registrations and rejects any name
  missing from this catalog.

Dependency-free on purpose (no jax, no yaml): the lint must import it
anywhere, including doc-only CI.
"""

from __future__ import annotations

from dataclasses import dataclass

from walkai_nos_tpu.obs.metrics import log_buckets

__all__ = ["CATALOG", "MetricSpec", "router_specs", "serving_specs"]


@dataclass(frozen=True)
class MetricSpec:
    name: str
    kind: str  # counter | gauge | histogram
    help: str
    labels: tuple[str, ...] = ()
    component: str = "serving"  # serving | router | kube | install | client
    buckets: tuple[float, ...] | None = None
    attr: str = ""  # bundle attribute name (serving/router specs only)


# Sub-ms floor for decode-pace style latencies (TPOT on a fast chip is
# ~0.1-0.5 ms/token); the engine's own dispatch sync sits in the ms
# range; request walls run to the 120 s server timeout.
_FAST = log_buckets(1e-4, 10.0)
_MID = log_buckets(1e-4, 100.0)
_SLOW = log_buckets(1e-3, 100.0)

CATALOG: tuple[MetricSpec, ...] = (
    # -- serving engine (models/serve.py via obs/serving.py) -----------
    MetricSpec(
        "cb_requests_submitted_total", "counter",
        "Requests accepted by ContinuousBatcher.submit()",
        attr="submitted",
    ),
    MetricSpec(
        "cb_requests_completed_total", "counter",
        "Finished requests by termination reason",
        labels=("reason",),  # eos | budget | pool_overflow (truncated)
        attr="completed",
    ),
    MetricSpec(
        "cb_request_errors_total", "counter",
        "Failed or rejected requests by reason",
        # oversize_reject | pool_overflow | bad_request | draining |
        # generation_timeout | client_disconnect | engine_failure
        labels=("reason",),
        attr="errors",
    ),
    MetricSpec(
        "cb_tokens_emitted_total", "counter",
        "Generated tokens handed to the host across all requests",
        attr="tokens",
    ),
    MetricSpec(
        "cb_queue_depth", "gauge",
        "Requests submitted but not yet admitted to a slot",
        attr="queue_depth",
    ),
    MetricSpec(
        "cb_slots_active", "gauge",
        "Slots holding a live decoding request at the last dispatch",
        attr="slots_active",
    ),
    MetricSpec(
        "cb_prefill_lane_active", "gauge",
        "Requests mid-prefill on the chunked prefill lane",
        attr="lane_active",
    ),
    MetricSpec(
        "cb_prefill_lane_rows_total", "counter",
        "Prefill-lane rows carrying a real admission, summed over "
        "lane dispatches",
        attr="lane_rows",
    ),
    MetricSpec(
        "cb_prefill_lane_row_capacity_total", "counter",
        "Configured prefill-lane rows available, summed over lane "
        "dispatches (utilization denominator)",
        attr="lane_capacity",
    ),
    # -- sequence-parallel prefill lane (models/serve.py sp mode) ------
    MetricSpec(
        "cb_prefill_sp_requests_total", "counter",
        "Long prompts (>= sp_min_tokens) admitted onto the dedicated "
        "sequence-parallel prefill lane",
        attr="sp_requests",
    ),
    MetricSpec(
        "cb_prefill_sp_rows_total", "counter",
        "Lane rows claimed by sequence-parallel fan-out (each row one "
        "chunk window of a long prompt), summed over lane dispatches "
        "in which a long entry fanned wider than one row",
        attr="sp_rows",
    ),
    MetricSpec(
        "cb_prefill_sp_active", "gauge",
        "Sequence-parallel (long-prompt) entries currently "
        "prefilling — 0 or 1 under the dedicated-long-lane policy",
        attr="sp_active",
    ),
    MetricSpec(
        "cb_prefill_sp_holds_total", "counter",
        "Admission turns in which a long prompt waited for the "
        "dedicated long lane while shorter prompts admitted around "
        "it (the length-aware starvation protection firing)",
        attr="sp_holds",
    ),
    # -- batched multi-LoRA serving (models/lora.py via serve.py) ------
    MetricSpec(
        "cb_lora_requests_total", "counter",
        "Requests accepted by a LoRA-armed engine, by serving "
        "adapter id (0 = the base model) — the multi-tenant traffic "
        "mix; only written on armed engines",
        labels=("adapter",),
        attr="lora_requests",
    ),
    MetricSpec(
        "cb_lora_resident_adapters", "gauge",
        "Adapters resident in the engine's stacked device arrays, "
        "the base identity (id 0) included; moves on hot load/unload",
        attr="lora_resident",
    ),
    MetricSpec(
        "cb_lora_gather_dispatches_total", "counter",
        "Step-program dispatches that carried the batched "
        "adapter-gather einsums (one count per armed dispatch, "
        "whatever the batch's adapter mix — the flat-overhead "
        "denominator behind the bench's cb_lora_overhead_pct)",
        attr="lora_gather",
    ),
    MetricSpec(
        "cb_lora_adapter_load_seconds_total", "counter",
        "Cumulative host seconds spent hot-loading adapter weights "
        "(validate + fold alpha into B + re-upload of the stacked "
        "tree)",
        attr="lora_load_seconds",
    ),
    MetricSpec(
        "cb_kv_pool_blocks", "gauge",
        "Paged KV pool blocks by state (scratch block excluded)",
        labels=("state",),  # free | used | parked
        attr="pool_blocks",
    ),
    MetricSpec(
        "cb_kv_pool_blocks_min_free", "gauge",
        "Low watermark of reclaimable pool blocks (free + evictable "
        "parked) since engine start",
        attr="pool_min_free",
    ),
    MetricSpec(
        "cb_prefix_blocks_hit_total", "counter",
        "Full prompt blocks served from the shared prefix cache at "
        "admission (zero prefill compute, zero HBM writes)",
        attr="prefix_hits",
    ),
    MetricSpec(
        "cb_prefix_blocks_miss_total", "counter",
        "Full prompt blocks prefilled fresh despite being lookupable "
        "(hit-rate denominator together with hits)",
        attr="prefix_misses",
    ),
    MetricSpec(
        "cb_prefix_evictions_total", "counter",
        "Parked prefix-cache blocks evicted (LRU, leaf-first) to "
        "back new allocations",
        attr="prefix_evictions",
    ),
    MetricSpec(
        "cb_prefix_cached_tokens", "gauge",
        "Prompt tokens resident in the prefix index (shared + parked "
        "blocks x 128)",
        attr="prefix_cached_tokens",
    ),
    MetricSpec(
        "cb_prefix_prefill_tokens_saved_total", "counter",
        "Prompt tokens the chunked prefill lane skipped thanks to "
        "prefix-cache hits",
        attr="prefix_saved",
    ),
    MetricSpec(
        "cb_prefix_prompt_tokens_total", "counter",
        "Prompt tokens of requests admitted while the prefix cache "
        "is enabled (saved-fraction denominator)",
        attr="prefix_prompt_tokens",
    ),
    MetricSpec(
        "cb_spec_draft_dispatches_total", "counter",
        "Draft-model forwards dispatched by speculative serving "
        "rounds (k scan steps + 1 lookahead K/V write per round)",
        attr="spec_draft",
    ),
    MetricSpec(
        "cb_spec_verify_dispatches_total", "counter",
        "Target multi-step verify dispatches (one per speculative "
        "round)",
        attr="spec_verify",
    ),
    MetricSpec(
        "cb_spec_slot_rounds_total", "counter",
        "(live slot, speculative round) pairs — the per-slot-round "
        "denominator for acceptance and commit averages",
        attr="spec_rounds",
    ),
    MetricSpec(
        "cb_spec_proposed_tokens_total", "counter",
        "Draft tokens proposed to live slots (acceptance-rate "
        "denominator)",
        attr="spec_proposed",
    ),
    MetricSpec(
        "cb_spec_accepted_tokens_total", "counter",
        "Draft tokens the target verify accepted (acceptance-rate "
        "numerator)",
        attr="spec_accepted",
    ),
    MetricSpec(
        "cb_spec_commit_tokens_per_round", "histogram",
        "Tokens the verify committed per live slot per speculative "
        "round (accepted drafts + the bonus token, 1..k+1) — "
        "device-side counts, like every cb_spec_* acceptance metric: "
        "a round that ends its request mid-window (EOS or budget) "
        "still counts the full verified window; realized emission is "
        "cb_tokens_total",
        buckets=(1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0, 8.0),
        attr="spec_emitted",
    ),
    MetricSpec(
        "cb_spec_k", "gauge",
        "Current draft length k chosen by the acceptance-adaptive "
        "controller",
        attr="spec_k_gauge",
    ),
    MetricSpec(
        "cb_spec_drafting_disabled", "gauge",
        "1 once the acceptance-adaptive controller has disabled "
        "drafting for this engine (0 while drafting)",
        attr="spec_disabled",
    ),
    MetricSpec(
        "cb_loop_dispatches_total", "counter",
        "Device-resident multi-step loop dispatches (one host sync "
        "folding up to loop_steps decode chunks or spec rounds)",
        attr="loop_dispatches",
    ),
    MetricSpec(
        "cb_loop_chunks_total", "counter",
        "Decode chunks / speculative rounds folded into "
        "device-resident loop dispatches (fold-depth numerator; "
        "denominator cb_loop_dispatches_total)",
        attr="loop_chunks",
    ),
    MetricSpec(
        "cb_loop_exits_total", "counter",
        "Device-resident loop exits by first-hit condition",
        # slot_done (EOS or budget) | unbacked (write head would
        # cross into an unbacked block) | horizon (loop_steps)
        labels=("reason",),
        attr="loop_exits",
    ),
    MetricSpec(
        "cb_loop_steps_per_sync", "gauge",
        "Per-slot device steps surfaced per device-resident loop "
        "sync, averaged over the run (the host-dispatch amortization "
        "factor the loop buys)",
        attr="loop_steps_per_sync",
    ),
    MetricSpec(
        "cb_admission_stall_seconds_total", "counter",
        "Cumulative host seconds inside admission work (dense mode: "
        "blocking prefill+admit dispatches; paged: bookkeeping only)",
        attr="stall",
    ),
    MetricSpec(
        "cb_dispatches_total", "counter",
        "Step-program dispatches issued",
        attr="dispatches",
    ),
    MetricSpec(
        "cb_dispatch_latency_seconds", "histogram",
        "Dispatch issue to host sync of its chunk (includes one "
        "chunk of pipelining overlap by design)",
        buckets=_MID,
        attr="dispatch_latency",
    ),
    MetricSpec(
        "cb_ttft_seconds", "histogram",
        "Submit to first token known to the host (its chunk sync)",
        buckets=_SLOW,
        attr="ttft",
    ),
    MetricSpec(
        "cb_tpot_seconds", "histogram",
        "Per-request mean time per output token after the first "
        "(decode pace)",
        buckets=_FAST,
        attr="tpot",
    ),
    MetricSpec(
        "cb_request_wall_seconds", "histogram",
        "Submit to completion wall time per finished request",
        buckets=_SLOW,
        attr="wall",
    ),
    MetricSpec(
        "cb_busy_slot_steps_total", "counter",
        "Slot-steps dispatched with a live request in the slot",
        attr="busy_steps",
    ),
    MetricSpec(
        "cb_slot_steps_total", "counter",
        "Slot-steps dispatched in total (occupancy denominator)",
        attr="total_steps",
    ),
    MetricSpec(
        "cb_kv_dispatch_bytes_total", "counter",
        "Sum over dispatches of KV cache bytes backing resident "
        "tokens (dispatch-weighted-average numerator)",
        attr="kv_bytes",
    ),
    MetricSpec(
        "cb_kv_dispatch_resident_tokens_total", "counter",
        "Sum over dispatches of resident tokens "
        "(dispatch-weighted-average denominator)",
        attr="kv_resident",
    ),
    MetricSpec(
        "cb_kv_bytes_per_resident_token", "gauge",
        "Latest per-dispatch snapshot of KV cache HBM bytes backing "
        "each resident token",
        attr="kv_ratio",
    ),
    MetricSpec(
        "cb_kv_cache_bytes_total", "counter",
        "KV pool backing bytes allocated at engine build, by storage "
        "dtype (quantized pools split into int8 data and their "
        "parallel f32 scale tiles; a second engine on the registry "
        "adds its own)",
        labels=("dtype",),  # int8 | bfloat16 | float32 | scale-f32
        attr="kv_cache_bytes",
    ),
    MetricSpec(
        "cb_quant_dequant_seconds_total", "counter",
        "Host seconds in quantization work (the one-time weight-tree "
        "quantization at engine build; device-side dequant is fused "
        "into the kernels and is attributed to "
        "cb_device_time_seconds_total, not here)",
        attr="quant_seconds",
    ),
    # -- capture/replay plane (obs/capture.py) -------------------------
    MetricSpec(
        "cb_capture_records_total", "counter",
        "Capture-log records written to the on-disk ring, by record "
        "kind (submit = accepted request inputs, done = completion "
        "token stream + digest)",
        labels=("kind",),  # submit | done
        attr="capture_records",
    ),
    MetricSpec(
        "cb_capture_bytes_total", "counter",
        "Capture-log bytes written (headers included; rotation may "
        "later prune whole files — this counts what was written, "
        "cb_capture_dropped_total counts what rotation lost)",
        attr="capture_bytes",
    ),
    MetricSpec(
        "cb_capture_dropped_total", "counter",
        "Capture records lost, by reason: a capture that silently "
        "lost records would masquerade as a complete incident record",
        labels=("reason",),  # rotated (pruned with an expired file) |
        # write_error (disk write failed; serving continues)
        attr="capture_dropped",
    ),
    MetricSpec(
        "cb_last_dispatch_unixtime_seconds", "gauge",
        "Unix time of the most recent engine dispatch (scrape-side "
        "staleness = now - value)",
        attr="last_dispatch",
    ),
    # -- tensor-parallel serving (models/serve.py, cfg.tp_devices) -----
    MetricSpec(
        "cb_tp_devices", "gauge",
        "Tensor-parallel shard count of the serving mesh (1 = "
        "single-chip engine; set once at engine build)",
        attr="tp_devices_gauge",
    ),
    MetricSpec(
        "cb_ici_bytes_per_step", "gauge",
        "Analytic ICI bytes one batch step moves through the "
        "tensor-parallel psums (2 per layer, ring all-reduce cost "
        "per live slot; only written on tp > 1 engines)",
        attr="ici_step_bytes",
    ),
    # -- device-time attribution (obs/attrib.py) -----------------------
    MetricSpec(
        "cb_dispatch_kind_total", "counter",
        "Dispatches by composition class",
        # decode | prefill | mixed | spec | spec_prefill
        labels=("kind",),
        attr="dispatch_kind",
    ),
    MetricSpec(
        "cb_device_time_seconds_total", "counter",
        "Cumulative blocked-device-sync seconds by dispatch "
        "composition (the device time the host could not overlap)",
        labels=("kind",),
        attr="device_time",
    ),
    MetricSpec(
        "cb_host_time_seconds_total", "counter",
        "Cumulative host dispatch-assembly seconds by dispatch "
        "composition (prologue, lane packing, program issue, "
        "epilogue bookkeeping)",
        labels=("kind",),
        attr="host_time",
    ),
    MetricSpec(
        "cb_device_sync_seconds", "histogram",
        "Blocked host time in one dispatch's device sync (the token "
        "fetch; pipelined chunks overlap part of the device time, "
        "speculative rounds are fully synchronous)",
        buckets=_MID,
        attr="device_sync",
    ),
    MetricSpec(
        "cb_device_step_ms", "gauge",
        "Device-attributed milliseconds per batch step over the "
        "trailing attribution window (device sync seconds / per-slot "
        "step window, averaged)",
        attr="device_step_ms",
    ),
    MetricSpec(
        "cb_host_overhead_frac", "gauge",
        "Host assembly fraction of total step time over the trailing "
        "attribution window (host / (host + device))",
        attr="host_overhead",
    ),
    MetricSpec(
        "cb_device_roofline_fraction", "gauge",
        "Analytic HBM-streaming floor over measured device time, "
        "trailing window (1.0 = decode runs at the memory roofline; "
        "unset on hosts with no published bandwidth)",
        attr="device_roofline",
    ),
    MetricSpec(
        "cb_device_hbm_bytes_per_step", "gauge",
        "Latest analytic HBM bytes one decode step must stream "
        "(weights + resident KV — the roofline fraction's numerator "
        "input)",
        attr="hbm_step_bytes",
    ),
    # -- sliding-window SLO / saturation (obs/slo.py) ------------------
    MetricSpec(
        "cb_slo_ttft_p50", "gauge",
        "TTFT p50 over the trailing SLO window (seconds, one "
        "log-bucket accuracy)",
        attr="slo_ttft_p50",
    ),
    MetricSpec(
        "cb_slo_ttft_p99", "gauge",
        "TTFT p99 over the trailing SLO window (seconds, one "
        "log-bucket accuracy)",
        attr="slo_ttft_p99",
    ),
    MetricSpec(
        "cb_slo_tpot_p99", "gauge",
        "Per-request decode pace p99 over the trailing SLO window "
        "(seconds per output token)",
        attr="slo_tpot_p99",
    ),
    MetricSpec(
        "cb_slo_dispatch_p99", "gauge",
        "Dispatch latency p99 over the trailing SLO window (seconds)",
        attr="slo_dispatch_p99",
    ),
    MetricSpec(
        "cb_slo_ok", "gauge",
        "1 when the labeled objective met its error budget over the "
        "window, 0 on breach (absent until the window has samples)",
        labels=("objective",),  # ttft_p99_s | tpot_p99_s
        attr="slo_ok_gauge",
    ),
    MetricSpec(
        "cb_slo_burn_rate", "gauge",
        "Error-budget burn of the labeled objective: fraction of "
        "window samples over the threshold divided by the quantile's "
        "budget (1.0 = burning exactly at budget)",
        labels=("objective",),
        attr="slo_burn",
    ),
    MetricSpec(
        "cb_saturation", "gauge",
        "Composed engine saturation in [0, 1]: max of the normalized "
        "pressure components (the router/autoscaler scale signal)",
        attr="saturation",
    ),
    MetricSpec(
        "cb_saturation_component", "gauge",
        "Normalized pressure component of cb_saturation",
        # busy | queue | queue_trend | pool
        labels=("signal",),
        attr="saturation_component",
    ),
    # -- KV block transfer plane (models/serve.py export/import) -------
    MetricSpec(
        "cb_xfer_exported_blocks_total", "counter",
        "Prefix blocks serialized out of this engine by "
        "export_blocks (ready trie nodes only; unknown or unready "
        "hashes are omitted, not counted)",
        attr="xfer_exported",
    ),
    MetricSpec(
        "cb_xfer_imported_blocks_total", "counter",
        "Prefix blocks landed in this engine's pool + trie by "
        "import_blocks (each grafted, tile-written, then parked — "
        "matchable exactly like a locally-prefilled block)",
        attr="xfer_imported",
    ),
    MetricSpec(
        "cb_xfer_import_rejected_total", "counter",
        "Imported blocks not landed, by reason",
        # dup (already resident) | orphan (parent block not resident)
        # | dry (pool exhausted even after LRU eviction) | a header
        # field name / shape / dtype / draft (incompatible payload,
        # rejects whole)
        labels=("reason",),
        attr="xfer_rejected",
    ),
    MetricSpec(
        "cb_xfer_bytes_total", "counter",
        "Decoded K/V tile bytes moved by the block-transfer plane, "
        "by direction",
        labels=("dir",),  # in | out
        attr="xfer_bytes",
    ),
    MetricSpec(
        "cb_xfer_migrated_requests_total", "counter",
        "Resident requests evacuated (dir=out, export_resident) or "
        "restored (dir=in, import_resident) by live migration — "
        "resubmitted and slot-restored requests both count",
        labels=("dir",),  # in | out
        attr="xfer_migrated",
    ),
    # -- fleet router (walkai_nos_tpu/router via obs/router.py) --------
    MetricSpec(
        "router_requests_total", "counter",
        "Requests accepted and routed by the fleet router",
        component="router",
        attr="submitted",
    ),
    MetricSpec(
        "router_routed_total", "counter",
        "Routing decisions by policy arm",
        # affinity (prefix-affinity map hit) | p2c (power-of-two-
        # choices fallback) | round_robin (baseline policy)
        labels=("policy",),
        component="router",
        attr="routed",
    ),
    MetricSpec(
        "router_requests_failed_total", "counter",
        "Requests the router could not place, by reason",
        # no_replica (fleet empty or all draining) | bad_request
        # (replica-side submit validation rejected it)
        labels=("reason",),
        component="router",
        attr="failed",
    ),
    MetricSpec(
        "router_replicas", "gauge",
        "Fleet replicas by lifecycle state",
        labels=("state",),  # active | draining
        component="router",
        attr="replicas_gauge",
    ),
    MetricSpec(
        "router_replica_saturation", "gauge",
        "Last observed composed saturation per replica (the engine's "
        "cb_saturation, read through the replica interface)",
        labels=("replica",),
        component="router",
        attr="replica_saturation",
    ),
    MetricSpec(
        "router_queue_depth", "gauge",
        "Requests submitted but not yet admitted, summed over the "
        "fleet's replicas",
        component="router",
        attr="queue_depth",
    ),
    MetricSpec(
        "router_prefix_hit_rate", "gauge",
        "Fleet-level shared-prefix block hit rate: prefix-cache hits "
        "over lookupable blocks summed across every replica that ever "
        "served (retired replicas' tallies included)",
        component="router",
        attr="prefix_hit_rate",
    ),
    MetricSpec(
        "router_scale_events_total", "counter",
        "Autoscaling reconciler actions by direction",
        # up (slice acquired, replica joined) | down (drain initiated)
        # | denied (scale-up wanted, provider had no capacity)
        labels=("direction",),
        component="router",
        attr="scale_events",
    ),
    # -- fleet observability plane (obs/anomaly.py, obs/federation.py) -
    MetricSpec(
        "router_fleet_capacity_slots", "gauge",
        "Decode slots summed over active (non-draining) replicas — "
        "the fleet's aggregate admission capacity",
        component="router",
        attr="fleet_capacity",
    ),
    MetricSpec(
        "router_roofline_fraction_spread", "gauge",
        "Max minus min of per-replica cb_device_roofline_fraction "
        "across active replicas (absent until two replicas report; a "
        "wide spread singles out one degraded replica or TP shard "
        "where the fleet mean dilutes it)",
        component="router",
        attr="roofline_spread",
    ),
    MetricSpec(
        "router_replica_anomaly", "gauge",
        "1 while the replica is flagged as a fleet straggler by the "
        "EWMA z-score detector (obs/anomaly.py), else 0; dropped at "
        "retirement like every per-replica series",
        labels=("replica",),
        component="router",
        attr="replica_anomaly",
    ),
    MetricSpec(
        "router_replica_anomaly_score", "gauge",
        "EWMA z-score of the replica's windowed dispatch p99 / "
        "device step ms / roofline fraction against the peer median "
        "(higher = worse; the routing load penalty's input)",
        labels=("replica",),
        component="router",
        attr="replica_anomaly_score",
    ),
    MetricSpec(
        "router_replica_scrape_errors_total", "counter",
        "Failed HTTP replica telemetry scrapes by endpoint kind — a "
        "flapping pod shows up here instead of silently reading as "
        "unreachable",
        labels=("replica", "kind"),  # healthz | stats | metrics
        component="router",
        attr="scrape_errors",
    ),
    MetricSpec(
        "router_flight_dumps_total", "counter",
        "Flight-recorder bundles written to the on-disk ring, by "
        "trigger",
        labels=("trigger",),  # anomaly | slo_breach
        component="router",
        attr="flight_dumps",
    ),
    # -- router block-shipping / migration (router/core.py) ------------
    MetricSpec(
        "router_xfer_ships_total", "counter",
        "Block-shipping transfers the router brokered (one source "
        "export landed in one destination import), by outcome",
        labels=("outcome",),  # ok | empty (nothing to ship) | error
        component="router",
        attr="xfer_ships",
    ),
    MetricSpec(
        "router_xfer_blocks_shipped_total", "counter",
        "Prefix blocks the destination replica reported imported "
        "across all router-brokered ships",
        component="router",
        attr="xfer_blocks_shipped",
    ),
    MetricSpec(
        "router_xfer_bytes_total", "counter",
        "Decoded K/V tile payload bytes moved by router-brokered "
        "block ships, by tile storage dtype (int8 pools ship their "
        "data tiles at ~2x fewer bytes than bf16; their f32 scale "
        "tiles count under their own dtype) — the wire-saving "
        "measurement for quantized shipping",
        labels=("dtype",),
        component="router",
        attr="xfer_bytes",
    ),
    MetricSpec(
        "router_xfer_failures_total", "counter",
        "Router-brokered transfers that raised on either side, by "
        "kind",
        labels=("kind",),  # ship (prefix blocks) | migrate (resident)
        component="router",
        attr="xfer_failures",
    ),
    MetricSpec(
        "router_xfer_migrations_total", "counter",
        "Resident requests the router moved between replicas via "
        "export_resident/import_resident, by outcome",
        # moved (drain evacuation landed on a peer) | returned (no
        # peer could take them; re-imported into the draining
        # source) | decode (two-stage handoff: a prefill replica's
        # first-token stream moved to its decode placement)
        labels=("outcome",),
        component="router",
        attr="xfer_migrations",
    ),
    # -- shadow/canary plane (router/core.py via obs/canary.py) --------
    MetricSpec(
        "router_canary_mirrored_total", "counter",
        "Live submits mirrored to the canary replica (the sampled "
        "shadow copies; the primary's response serves the user)",
        component="router",
        attr="canary_mirrored",
    ),
    MetricSpec(
        "router_canary_compared_total", "counter",
        "Primary/mirror completion pairs compared at the completion "
        "seam, by result",
        # match (digest-identical streams) | divergent (token values
        # differ inside the common prefix) | latency_only (config
        # delta declares the serving function moved; no digest gate)
        # | mirror_error (the canary side failed — operational, not a
        # divergence)
        labels=("result",),
        component="router",
        attr="canary_compared",
    ),
    MetricSpec(
        "router_canary_divergence_total", "counter",
        "Mirrored completions whose token stream diverged from the "
        "primary's under an armed digest-exact gate — each one dumps "
        "a flight bundle and rejects the canary",
        component="router",
        attr="canary_divergence",
    ),
    MetricSpec(
        "router_canary_mirror_errors_total", "counter",
        "Mirror submits or completions that failed on the canary "
        "side (submit rejected, replica error) — counted apart from "
        "divergences because a sick canary is operational news, not "
        "a correctness verdict",
        component="router",
        attr="canary_mirror_errors",
    ),
    MetricSpec(
        "router_canary_verdict", "gauge",
        "Canary verdict machine state (1 on the current state, 0 on "
        "the rest)",
        labels=("state",),  # warming | observing | promote | reject
        component="router",
        attr="canary_verdict",
    ),
    MetricSpec(
        "router_canary_latency_delta_pct", "gauge",
        "Windowed canary-minus-primary latency delta as a percent of "
        "the primary's quantile (positive = canary slower), per "
        "latency metric",
        labels=("metric",),  # ttft_p99 | tpot_p99
        component="router",
        attr="canary_latency_delta",
    ),
    # -- kube binaries (kube/runtime.py via health.Metrics) ------------
    MetricSpec(
        "nos_reconcile_total", "counter",
        "Reconciliations per controller and outcome",
        labels=("controller", "result"),
        component="kube",
    ),
    MetricSpec(
        "nos_reconcile_seconds_sum", "counter",
        "Cumulative reconcile wall time",
        labels=("controller",),
        component="kube",
    ),
    # -- demo bench client (demos/tpu-sharing-comparison/client) -------
    MetricSpec(
        "inference_time_seconds_sum", "counter",
        "Cumulative inference seconds per target (summary numerator; "
        "reference-repo comparison query shape)",
        labels=("target",),
        component="client",
    ),
    MetricSpec(
        "inference_time_seconds_count", "counter",
        "Completed inference requests per target (summary denominator)",
        labels=("target",),
        component="client",
    ),
    MetricSpec(
        "inference_errors_total", "counter",
        "Failed inference requests per target",
        labels=("target",),
        component="client",
    ),
    # -- install exporter (cmd/metricsexporter.py) ---------------------
    MetricSpec(
        "nos_install_info", "gauge",
        "Install identity (value is always 1)",
        labels=("installation_uuid",),
        component="install",
    ),
    MetricSpec(
        "nos_install_component_enabled", "gauge",
        "1 if the chart component is enabled, else 0",
        labels=("component",),
        component="install",
    ),
    MetricSpec(
        "nos_install_node_capacity", "gauge",
        "Node capacity by resource, parsed from the Kube quantity",
        labels=("node", "resource"),
        component="install",
    ),
    MetricSpec(
        "nos_install_nodes", "gauge",
        "Nodes in the install inventory",
        component="install",
    ),
)


def serving_specs() -> tuple[MetricSpec, ...]:
    return tuple(s for s in CATALOG if s.component == "serving")


def router_specs() -> tuple[MetricSpec, ...]:
    return tuple(s for s in CATALOG if s.component == "router")


def _check() -> None:
    names = [s.name for s in CATALOG]
    if len(names) != len(set(names)):
        raise ValueError("duplicate metric names in CATALOG")
    for component, specs in (
        ("serving", serving_specs()),
        ("router", router_specs()),
    ):
        attrs = [s.attr for s in specs]
        if "" in attrs or len(attrs) != len(set(attrs)):
            raise ValueError(
                f"{component} specs need unique non-empty attrs"
            )


_check()

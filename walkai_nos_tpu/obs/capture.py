"""Deterministic capture plane: a black-box request recorder whose
captures replay token-identically offline.

The serving engine's defining invariant is that every request's output
is a pure function of (weights, prompt, sampling knobs, seed) —
independent of batch composition, chunking, spec rounds, loop folding,
TP sharding, and quantization-sim. `CaptureLog` turns that invariant
into an OPERATIONAL artifact: a bounded, rotating on-disk log whose
header pins the engine's config fingerprint (every determinism-relevant
knob plus a weights digest) and whose per-request records pin exactly
the inputs the invariant quantifies over — so any capture can be
re-executed by `sim/replay.py` and verified token for token, and any
production incident becomes a reproducible artifact instead of a
one-shot event.

File format — one JSON object per line (ndjson), every file
self-contained:

    {"kind": "header", "version": 1, "fingerprint": {...},
     "created_unix_s": ...}
    {"kind": "submit", "rid": ..., "trace_id": ..., "prompt": [...],
     "max_new_tokens": ..., "eos_id": ..., "temperature": ...,
     "top_k": ..., "top_p": ..., "seed": <EFFECTIVE seed>,
     "arrival_s": <monotonic offset from capture origin>}
    {"kind": "done", "rid": ..., "trace_id": ..., "tokens": [...],
     "n_tokens": ..., "digest": "crc32:...", "ttft_s": ...,
     "wall_s": ..., "truncated": ..., "reason": ...}

`seed` is the EFFECTIVE per-request seed (the engine defaults an
unset seed to the request id), so a replay under fresh request ids
reproduces the original PRNG streams bit for bit. `tokens` rides the
done record beside its digest on purpose: the digest is the cheap
zero-divergence check, the token list is what first-divergence triage
needs to pin the exact (request, token) where a replay forked.

Rotation keeps the recorder bounded on a long-running server: when
the current file passes `max_bytes` it closes and a fresh file (with
its own header) opens; files beyond `max_files` are pruned oldest
first, their records counted as dropped. Drops and write failures are
visible in the `cb_capture_*` catalog metrics — a capture that
silently lost records would masquerade as a complete incident record.

Writers: `ContinuousBatcher(capture=...)` records at its submit and
commit seams; `FleetRouter(capture=...)` records fleet-level traffic
(done records add the routed replica). `WALKAI_CAPTURE_DIR` arms
either binary; `/debug/capture` serves status / rotate / download.
Readers: `sim/replay.py` (`load_capture` / `replay_capture`),
`cmd/replay.py` (the one-command replay-and-triage CLI).

Stdlib + numpy only — no jax: the replay CLI's capture parsing and
doc-only CI must import this module anywhere.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time
import zlib

import numpy as np

__all__ = [
    "CaptureLog",
    "fingerprint_id",
    "rotate_action_from_body",
    "token_digest",
    "tree_crc32",
]


def rotate_action_from_body(raw: bytes) -> str:
    """Validate a /debug/capture POST body — the ONE action contract
    the demo server and the serverouter share (two hand-maintained
    copies of the parse/validate already existed; a new action added
    to one binary would silently 400 on the other). Raises ValueError
    (which JSONDecodeError subclasses) on anything but a JSON object
    requesting a supported action; the caller maps that to a 400."""
    body = json.loads(raw or b"{}")
    if not isinstance(body, dict):
        raise ValueError("body must be a JSON object")
    action = body.get("action", "rotate")
    if action != "rotate":
        raise ValueError(
            f"unknown action {action!r} (supported: rotate)"
        )
    return action

_FILE_RE = re.compile(r"^capture-(\d+)\.jsonl$")


def token_digest(tokens) -> str:
    """Digest of one request's output token stream: CRC-32 over the
    int32 little-endian token bytes — byte-identical streams and only
    byte-identical streams agree, and the check costs microseconds
    per request at capture AND at replay verification."""
    arr = np.asarray(list(tokens), dtype="<i4")
    return f"crc32:{zlib.crc32(arr.tobytes()):08x}"


def tree_crc32(tree) -> int:
    """Content digest of a parameter pytree: CRC-32 accumulated over
    every leaf's path, dtype, shape, and raw bytes, leaves visited in
    path-sorted order so the digest is independent of dict insertion
    order. Sharded (tensor-parallel) leaves gather to host first —
    the digest names the LOGICAL weights, not their placement."""
    import jax

    crc = 0
    leaves = sorted(
        jax.tree_util.tree_leaves_with_path(tree),
        key=lambda kv: jax.tree_util.keystr(kv[0]),
    )
    for path, leaf in leaves:
        a = np.ascontiguousarray(np.asarray(leaf))
        crc = zlib.crc32(jax.tree_util.keystr(path).encode(), crc)
        crc = zlib.crc32(
            f"{a.dtype}:{a.shape}".encode(), crc
        )
        crc = zlib.crc32(a.tobytes(), crc)
    return crc


def fingerprint_id(fingerprint: dict) -> str:
    """Short stable id of a config fingerprint: sha1 over the
    canonical (sorted-keys) JSON of every field except `id` itself.
    12 hex chars — enough to correlate a logged completion with the
    capture that can replay it, short enough to ride every record."""
    body = {k: v for k, v in fingerprint.items() if k != "id"}
    blob = json.dumps(body, sort_keys=True, default=str).encode()
    return hashlib.sha1(blob).hexdigest()[:12]


class CaptureLog:
    """Bounded, rotating on-disk request recorder (ndjson ring).

    Thread-safe: the engine's driver thread writes records while a
    server handler thread may rotate or read status. Telemetry
    discipline: a failed write is counted (`write_error` drop) and
    swallowed — the recorder must never take serving down.
    """

    def __init__(
        self,
        directory: str,
        *,
        max_bytes: int = 16 << 20,
        max_files: int = 4,
    ):
        if max_bytes <= 0 or max_files <= 0:
            raise ValueError(
                f"max_bytes and max_files must be > 0; got "
                f"{max_bytes}, {max_files}"
            )
        self.dir = str(directory)
        self.max_bytes = max_bytes
        self.max_files = max_files
        self._lock = threading.Lock()
        self._fp = None  # current file object
        self._file = None  # current file name
        self._file_bytes = 0
        # name -> record count, for drop accounting when pruned.
        self._file_records: dict[str, int] = {}
        self._header_line: str | None = None
        self.fingerprint: dict | None = None
        self._origin: float | None = None
        self._obs = None  # ServingObs-shaped bundle (optional)
        self._records = {"submit": 0, "done": 0}
        self._bytes = 0
        self._dropped = {"rotated": 0, "write_error": 0}
        # Continue the sequence past whatever an earlier process left
        # in the directory, so two runs never collide on a file name.
        self._seq = self._max_existing_seq() + 1

    @classmethod
    def coerce(cls, value) -> "CaptureLog | None":
        """The ONE capture-argument contract every constructor
        (ContinuousBatcher, FleetRouter) applies: a directory path
        builds a log, a CaptureLog or None passes through, anything
        else is a loud ValueError — a silently-disabled incident
        recorder is discovered at the incident."""
        if isinstance(value, (str, os.PathLike)):
            return cls(os.fspath(value))
        if value is None or isinstance(value, cls):
            return value
        raise ValueError(
            "capture must be a CaptureLog, a directory path, or "
            f"None; got {type(value).__name__}"
        )

    @classmethod
    def from_env(cls, env=None) -> "CaptureLog | None":
        """The ONE env-arming rule every binary shares (demo server,
        serverouter): WALKAI_CAPTURE_DIR arms the recorder,
        WALKAI_CAPTURE_MAX_BYTES / WALKAI_CAPTURE_MAX_FILES bound the
        ring. None when unset — two copies of this mapping already
        drifted once (one binary silently ignoring the bounds
        knobs), so neither binary may reimplement it."""
        env = os.environ if env is None else env
        directory = env.get("WALKAI_CAPTURE_DIR")
        if not directory:
            return None
        return cls(
            directory,
            max_bytes=int(
                env.get("WALKAI_CAPTURE_MAX_BYTES", str(16 << 20))
            ),
            max_files=int(env.get("WALKAI_CAPTURE_MAX_FILES", "4")),
        )

    # -- lifecycle -----------------------------------------------------

    def attach(self, fingerprint: dict, *, obs=None) -> None:
        """Arm the log: pin the writer's config fingerprint (written
        as the header of every file) and start the arrival clock.
        `obs` is the engine's telemetry bundle — when given, the
        `cb_capture_*` instruments mirror the internal tallies."""
        self.fingerprint = fingerprint
        self._obs = obs
        self._origin = time.monotonic()
        self._header_line = json.dumps({
            "kind": "header",
            "version": 1,
            "fingerprint": fingerprint,
            "created_unix_s": time.time(),
        }, default=str)
        with self._lock:
            self._open_locked()

    @property
    def armed(self) -> bool:
        return self._origin is not None

    def arrival_offset(self, t_monotonic: float) -> float:
        """Monotonic seconds since the capture armed — the submit
        record's arrival timestamp (what original-timing replay
        re-paces against)."""
        if self._origin is None:
            return 0.0
        return max(0.0, t_monotonic - self._origin)

    # -- record writers ------------------------------------------------

    def record_submit(self, **fields) -> None:
        self._write("submit", fields)

    def record_done(self, **fields) -> None:
        self._write("done", fields)

    def _write(self, kind: str, fields: dict) -> None:
        line = json.dumps({"kind": kind, **fields}, default=str)
        with self._lock:
            if self._fp is None:
                self._open_locked()
            if self._fp is None:
                # Open itself failed (dir unwritable, disk full):
                # count the loss and keep serving — the recorder
                # must never take the engine's driver thread down.
                self._dropped["write_error"] += 1
                if self._obs is not None:
                    self._obs.capture_dropped.inc(
                        labels={"reason": "write_error"}
                    )
                return
            try:
                self._fp.write(line + "\n")
                self._fp.flush()
            except (OSError, ValueError):
                self._dropped["write_error"] += 1
                if self._obs is not None:
                    self._obs.capture_dropped.inc(
                        labels={"reason": "write_error"}
                    )
                return
            n = len(line) + 1
            self._file_bytes += n
            self._bytes += n
            self._file_records[self._file] = (
                self._file_records.get(self._file, 0) + 1
            )
            self._records[kind] = self._records.get(kind, 0) + 1
            if self._obs is not None:
                self._obs.capture_records.inc(labels={"kind": kind})
                self._obs.capture_bytes.inc(n)
            if self._file_bytes >= self.max_bytes:
                self._rotate_locked()

    # -- rotation ------------------------------------------------------

    def rotate(self) -> None:
        """Close the current file and start a fresh one (each file is
        self-contained behind its own header) — the /debug/capture
        rotate action, e.g. to freeze an incident's tail before
        downloading it."""
        with self._lock:
            self._rotate_locked()

    def _open_locked(self) -> None:
        # Exclusive create ("x") with a bump-and-retry: two processes
        # sharing one capture dir (a rolling restart's overlap) must
        # never truncate each other's live file — "w" would lose the
        # other process's records with no drop accounting.
        name = path = None
        try:
            os.makedirs(self.dir, exist_ok=True)
            for _ in range(10_000):
                name = f"capture-{self._seq:06d}.jsonl"
                self._seq += 1
                path = os.path.join(self.dir, name)
                try:
                    self._fp = open(path, "x")
                    break
                except FileExistsError:
                    continue
            else:
                raise OSError("no free capture sequence number")
            if self._header_line is not None:
                self._fp.write(self._header_line + "\n")
                self._fp.flush()
                self._file_bytes = len(self._header_line) + 1
                self._bytes += self._file_bytes
                if self._obs is not None:
                    self._obs.capture_bytes.inc(self._file_bytes)
            else:
                self._file_bytes = 0
        except OSError:
            # A failed HEADER write (ENOSPC after a successful
            # metadata-only open) must not abandon the fd or the
            # stray empty file: every later record re-enters here,
            # and leaked fds would eventually EMFILE the server —
            # the recorder taking serving down, its one forbidden
            # failure mode.
            if self._fp is not None:
                try:
                    self._fp.close()
                except OSError:
                    pass
                if path is not None:
                    try:
                        os.remove(path)
                    except OSError:
                        pass
            self._fp = None
            self._file = None
            return
        self._file = name
        self._file_records.setdefault(name, 0)

    def _rotate_locked(self) -> None:
        if self._fp is not None:
            try:
                self._fp.close()
            except OSError:
                pass
            self._fp = None
            self._file = None
        self._open_locked()
        self._prune_locked()

    def _prune_locked(self) -> None:
        # The ring bound applies to files THIS instance wrote: a
        # shared dir's older files may belong to a still-LIVE process
        # (rolling-restart overlap — the same scenario the exclusive
        # create guards), and unlinking its live file would lose its
        # records with zero drop accounting on either side. Foreign
        # files (dead runs' leftovers, replayable via --run) expire
        # only once the dir exceeds TWICE the ring — disk stays
        # bounded, an overlapping writer's ring is never touched
        # (it prunes itself to max_files).
        files = self._list_files()
        own = [n for n in files if n in self._file_records]
        while len(own) > self.max_files:
            victim = own.pop(0)
            lost = self._file_records.pop(victim, 0)
            # The header line is format, not payload — only request
            # records count as dropped capture data.
            self._count_drop_locked(lost)
            try:
                os.remove(os.path.join(self.dir, victim))
            except OSError:
                break
            files.remove(victim)
        foreign = [n for n in files if n not in self._file_records]
        while foreign and len(files) > 2 * self.max_files:
            victim = foreign.pop(0)
            self._count_drop_locked(self._count_records_in(victim))
            try:
                os.remove(os.path.join(self.dir, victim))
            except OSError:
                break
            files.remove(victim)

    def _count_drop_locked(self, lost: int) -> None:
        self._dropped["rotated"] += lost
        if self._obs is not None and lost:
            self._obs.capture_dropped.inc(
                lost, labels={"reason": "rotated"}
            )

    def _count_records_in(self, name: str) -> int:
        """Request records in a FOREIGN file about to expire (we
        never wrote it, so its count isn't in our books) — a dropped
        tally must never read as 'nothing lost' when a dead run's
        records go."""
        try:
            with open(os.path.join(self.dir, name)) as f:
                return sum(
                    1 for line in f
                    if line.strip() and '"kind": "header"' not in line
                )
        except OSError:
            return 0

    def _list_files(self) -> list[str]:
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        return sorted(n for n in names if _FILE_RE.match(n))

    def _max_existing_seq(self) -> int:
        best = 0
        for name in self._list_files():
            best = max(best, int(_FILE_RE.match(name).group(1)))
        return best

    # -- read surface --------------------------------------------------

    def files(self) -> list[str]:
        """Current capture file paths, oldest first."""
        with self._lock:
            return [
                os.path.join(self.dir, n) for n in self._list_files()
            ]

    def read_text(self) -> str:
        """Every retained file concatenated, oldest first — the
        /debug/capture download body (each file carries its own
        header, so the concatenation parses as one capture)."""
        parts = []
        for path in self.files():
            try:
                with open(path) as f:
                    parts.append(f.read())
            except OSError:
                continue
        return "".join(parts)

    def stats(self) -> dict:
        """The /debug/capture status payload (sans the owner's
        fingerprint id, which the engine/router adds)."""
        with self._lock:
            return {
                "dir": self.dir,
                "files": self._list_files(),
                "records": dict(self._records),
                "bytes": self._bytes,
                "dropped": dict(self._dropped),
                "max_bytes": self.max_bytes,
                "max_files": self.max_files,
            }

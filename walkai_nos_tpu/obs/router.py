"""RouterObs: the fleet router's telemetry bundle.

The router-level sibling of `obs/serving.ServingObs`: one registry
plus one instrument attribute per `component="router"` catalog spec
(`obs.submitted.inc()`, `obs.scale_events.inc(labels=...)`, ...),
built through the same instruments-from-catalog path
(`bind_catalog_instruments`), so the router contains no literal
metric names and `make metrics-lint` holds `obs/catalog.py` and
`docs/observability.md` to each other for the `router_*` series
exactly as it does for `cb_*`.

The router runs in its own process (cmd/serverouter.py) in a real
deployment — its registry is separate from any replica's by design;
`cmd/serverouter.py` serves it on the router's own `/metrics`. In CI
the in-process fleet shares the process with its engines but still
keeps the registries apart: fleet-level series aggregate across
replicas, per-engine series stay per-engine.

`enabled=False` builds the bundle in no-op mode, same contract as the
serving bundle (reads return zeros/None; views flag `obs_disabled`).
"""

from __future__ import annotations

from walkai_nos_tpu.obs.catalog import router_specs
from walkai_nos_tpu.obs.metrics import Registry
from walkai_nos_tpu.obs.serving import bind_catalog_instruments

__all__ = ["RouterObs"]


class RouterObs:
    def __init__(
        self,
        *,
        enabled: bool = True,
        registry: Registry | None = None,
    ):
        self.enabled = enabled
        self.registry = registry or Registry(enabled=enabled)
        bind_catalog_instruments(self, router_specs(), self.registry)

    def render(self) -> str:
        return self.registry.render()

"""Per-dispatch device-time attribution for the serving engine.

The continuous batcher's step time has three very different owners —
the device program itself, the host-side dispatch assembly (table
uploads, lane packing, registry writes), and whatever pipelining hides
— and ROADMAP item 3 needs them separated LIVE, not only in offline
bench runs (r5's slope decomposition found 0.23 ms of host dispatch
inside a 0.74 ms step, but only once per bench round). This module is
the always-on version of that decomposition, in the spirit of
continuous profiling in production (Google-Wide Profiling): cheap
enough to leave enabled, precise enough to act on.

Mechanics, all host-side at the engine's existing sync seams:

- **Classification**: every dispatch is labeled by its composition —
  plain decode slot-steps, prefill-lane chunks, both fused in one
  program, or a speculative draft+verify round (`classify_dispatch`;
  the `kind` label on every attribution series). A TTFT regression
  that lives only in `mixed` dispatches is a lane-interference story;
  one that lives in `spec` is a draft-cost story.
- **Host vs device split**: the engine measures the host time spent
  assembling each dispatch (prologue through program issue plus
  epilogue bookkeeping) separately from the BLOCKED device sync (the
  host fetch of the chunk's tokens). Under the engine's one-chunk
  pipelining the blocked sync is the residual device time the host
  could not overlap — exactly the quantity that bounds capacity;
  speculative rounds are synchronous, so there the sync is the whole
  device round.
- **Roofline lineage**: each dispatch's measured device time is paired
  with the same analytic HBM cost model the bench uses (weights
  re-read + resident KV per step over published bandwidth), so
  `cb_device_roofline_fraction` tracks continuously what
  `decode_gqa_roofline_fraction` records once per bench round. On
  hosts with no published bandwidth (CPU CI) the fraction is simply
  never set. The model is DTYPE-AWARE (`params_hbm_bytes` /
  `kv_hbm_bytes_per_token`): param bytes come from the tree's actual
  leaf storage and KV bytes from the pool's storage dtype plus its
  scale rows, so when int8 quantization halves the traffic the
  `cb_device_hbm_bytes_per_step` / `cb_device_roofline_fraction`
  gauges show the ceiling itself moving rather than flattering the
  old one.

Live gauges are maintained over a short trailing window of dispatches
(`window` — big enough to smooth one-off syncs, small enough to react
within seconds): `cb_device_step_ms`, `cb_host_overhead_frac`,
`cb_device_roofline_fraction`, `cb_device_hbm_bytes_per_step`.
Cumulative per-kind counters (`cb_dispatch_kind_total`,
`cb_device_time_seconds_total`, `cb_host_time_seconds_total`) and the
`cb_device_sync_seconds` histogram carry the full history for
dashboards. Everything no-ops when the obs bundle is disabled.
"""

from __future__ import annotations

from collections import deque

__all__ = [
    "DISPATCH_KINDS",
    "DispatchAttribution",
    "classify_dispatch",
    "kv_hbm_bytes_per_token",
    "params_hbm_bytes",
    "tp_ici_bytes_per_token",
]


def params_hbm_bytes(params) -> int:
    """HBM bytes one decode step streams for the weights: the param
    tree's ACTUAL storage bytes (leaf nbytes), not an element count
    times an assumed width — an int8-quantized tree (its f32 scale
    rows included) reports its true, smaller footprint, so the
    roofline gauges move when quantization moves the ceiling."""
    import jax

    return sum(
        int(getattr(leaf, "nbytes", 0))
        for leaf in jax.tree_util.tree_leaves(params)
    )


def kv_hbm_bytes_per_token(cfg) -> int:
    """Physical KV-cache HBM bytes backing one resident token, from
    the ACTUAL storage dtype (`LMConfig.kv_storage_dtype`), not a
    hardcoded 2 B/elem: per layer, K and V each store `head_dim`
    elements per kv head at the pool's item size, plus — for
    quantized pools (the fp32-sim arm included; its scale pools are
    physically resident too) — one f32 scale per row per head. The
    ONE per-token cost the analytic roofline model, `kv_stats()`,
    and `cb_kv_hbm_bytes_per_resident_token` all derive from."""
    head_dim = cfg.hidden_dim // cfg.num_heads
    item = cfg.kv_storage_dtype.itemsize
    scale_bytes = 4 if cfg.kv_quant else 0
    return cfg.num_layers * 2 * cfg.kv_heads * (
        head_dim * item + scale_bytes
    )

def tp_ici_bytes_per_token(cfg) -> int:
    """Analytic ICI bytes one slot-token moves through the
    tensor-parallel collectives: the Megatron layout pays exactly two
    psums per layer (the row-parallel out_proj and fc2 reduce their
    partial activations onto the residual), and a ring all-reduce of
    an N-byte activation moves 2*(tp-1)/tp * N bytes through each
    chip. 0 at tp <= 1 — the gauge this feeds reads zero on a
    single-chip engine by construction, and the roofline cost model
    adds nothing."""
    tp = getattr(cfg, "tp_devices", 1)
    if tp <= 1:
        return 0
    act_bytes = cfg.hidden_dim * cfg.compute_dtype.itemsize
    per_psum = 2 * (tp - 1) * act_bytes // tp
    return cfg.num_layers * 2 * per_psum


# Every value the `kind` label can take, in documentation order.
DISPATCH_KINDS = ("decode", "prefill", "mixed", "spec", "spec_prefill")


def classify_dispatch(
    busy_slots: int, lane_rows: int, spec: bool
) -> str:
    """Composition class of one dispatch: what the step program
    actually carried. `busy_slots` = slots holding a live request,
    `lane_rows` = prefill-lane rows carrying a real admission, `spec`
    = the dispatch was a speculative draft+verify round."""
    if spec:
        return "spec_prefill" if lane_rows else "spec"
    if lane_rows and busy_slots:
        return "mixed"
    if lane_rows:
        return "prefill"
    return "decode"


class DispatchAttribution:
    """Attribution recorder over a `ServingObs` bundle.

    One `record()` per dispatch, at its host sync (the only place both
    the host and device times are known). The cost model inputs are
    fixed at construction — weights are served once, KV bytes per
    token is a config constant — so the per-dispatch work is a handful
    of registry writes plus O(1) window-sum updates.
    """

    def __init__(
        self,
        obs,
        *,
        param_bytes: int = 0,
        kv_bytes_per_token: int = 0,
        hbm_bytes_per_s: float | None = None,
        ici_bytes_per_token: float = 0.0,
        window: int = 128,
    ):
        self.enabled = obs.enabled
        self._obs = obs
        # TP-aware inputs: on a tensor-parallel engine the caller
        # passes PER-SHARD weight and KV bytes (each chip streams
        # only its slices — the division by the shard count is the
        # CALLER's contract) plus the per-token ICI bytes of the two
        # per-layer psums, so the analytic floor stays the floor of
        # what ONE chip actually does and the roofline fraction
        # stays honest at tp > 1.
        self._param_bytes = float(param_bytes)
        self._kv_per_tok = float(kv_bytes_per_token)
        self._ici_per_tok = float(ici_bytes_per_token)
        self._bw = hbm_bytes_per_s or None
        if window <= 0:
            raise ValueError(f"window must be > 0; got {window}")
        self._window = window
        # Trailing window of (device_s, host_s, steps, ideal_s|None):
        # running sums maintained incrementally so a record is O(1).
        self._recent: deque[tuple] = deque()
        self._sum_device = 0.0
        self._sum_host = 0.0
        self._sum_steps = 0
        self._sum_ideal = 0.0
        self._last_bytes_per_step: float | None = None

    def record(
        self,
        *,
        kind: str,
        steps: int,
        host_s: float,
        device_s: float,
        resident_tokens: int,
        busy_slots: int = 0,
    ) -> None:
        """One dispatch: `steps` = its per-slot step window (chunk
        size for a plain chunk, k+1 for a speculative round), `host_s`
        = measured host assembly + bookkeeping, `device_s` = the
        blocked device sync, `resident_tokens` = KV-resident tokens
        at dispatch (the cost model's cache-read term), `busy_slots`
        = slots carrying a live request (the ICI term's token count —
        each live slot moves one activation through the psums per
        step)."""
        if not self.enabled:
            return
        obs = self._obs
        obs.dispatch_kind.inc(labels={"kind": kind})
        obs.device_time.inc(max(0.0, device_s), {"kind": kind})
        obs.host_time.inc(max(0.0, host_s), {"kind": kind})
        obs.device_sync.observe(device_s)
        if self._ici_per_tok:
            # Analytic ICI bytes one batch step moves through the TP
            # psums (0 series at tp=1: the gauge is only written on
            # TP engines).
            obs.ici_step_bytes.set(
                float(busy_slots) * self._ici_per_tok
            )
        ideal_s = None
        bytes_per_step = None
        if self._bw:
            # Analytic HBM floor of this dispatch: every decode step
            # re-reads the (per-shard) weights and resident KV once
            # (the same model bench_lm's decode ceiling uses, divided
            # by the shard count at tp > 1).
            bytes_per_step = (
                self._param_bytes + resident_tokens * self._kv_per_tok
            )
            ideal_s = steps * bytes_per_step / self._bw
            self._last_bytes_per_step = bytes_per_step
        self._recent.append((device_s, host_s, steps, ideal_s))
        self._sum_device += device_s
        self._sum_host += host_s
        self._sum_steps += steps
        self._sum_ideal += ideal_s or 0.0
        if len(self._recent) > self._window:
            d, h, st, ideal = self._recent.popleft()
            self._sum_device -= d
            self._sum_host -= h
            self._sum_steps -= st
            self._sum_ideal -= ideal or 0.0
        if self._sum_steps > 0:
            obs.device_step_ms.set(
                round(1e3 * self._sum_device / self._sum_steps, 4)
            )
        total = self._sum_device + self._sum_host
        if total > 0:
            obs.host_overhead.set(round(self._sum_host / total, 4))
        if bytes_per_step is not None:
            obs.hbm_step_bytes.set(bytes_per_step)
            if self._sum_ideal > 0 and self._sum_device > 0:
                obs.device_roofline.set(
                    round(
                        min(1.0, self._sum_ideal / self._sum_device), 4
                    )
                )

    def stats(self) -> dict:
        """Attribution view of the registry — the `/stats` `cb_attrib`
        section and the `/debug/state` `attrib` block. Same dict shape
        with telemetry off, flagged `obs_disabled` (the PR 3
        convention), so zeros read as "not recorded"."""
        obs = self._obs
        kinds = {
            kind: {
                "dispatches": int(
                    obs.dispatch_kind.value({"kind": kind})
                ),
                "device_s": round(
                    obs.device_time.value({"kind": kind}), 6
                ),
                "host_s": round(
                    obs.host_time.value({"kind": kind}), 6
                ),
            }
            for kind in DISPATCH_KINDS
        }
        return {
            **({} if self.enabled else {"obs_disabled": True}),
            "device_step_ms": obs.device_step_ms.value(),
            "host_overhead_frac": obs.host_overhead.value(),
            "roofline_fraction": obs.device_roofline.value(),
            "hbm_bytes_per_step": self._last_bytes_per_step,
            "window_dispatches": len(self._recent),
            "kinds": kinds,
        }

"""Sliding-window SLO views + the composed saturation signal.

PR 3's histograms are process-lifetime cumulative: perfect for
rate()-style dashboards, useless for "did p99 TTFT breach SLO over the
last 30 seconds" — after a day of traffic a latency regime change
moves the cumulative quantile by epsilon. This module adds the
windowed layer on top of the SAME histograms, with no second
observation path:

- **`BucketRing`**: a ring of cumulative-bucket-count snapshots of one
  `Histogram`, one snapshot per `window_s / buckets` seconds.
  `window_counts(now)` differences the live counts against the
  snapshot taken ~`window_s` ago, yielding the bucket counts of
  exactly the samples inside the window; quantiles over that delta
  inherit the registry's one-bucket-width accuracy. Snapshots older
  than the window expire (one is retained as the baseline); before a
  full window has elapsed, reads cover everything since start (a
  PARTIAL window, with its true span reported); an empty window reads
  as None, never 0. A snapshot is ~30 ints — a week of serving costs
  the same memory as a minute.
- **`SloTracker`**: the engine-facing bundle. `on_sync(now, ...)` at
  every dispatch sync advances the TTFT / TPOT / dispatch-latency
  rings (cheap: one float compare until a bucket boundary passes) and,
  at a throttled cadence (`refresh_s`), recomputes the windowed
  quantile gauges (`cb_slo_ttft_p99` et al.), the per-objective
  compliance bits (`cb_slo_ok{objective}`) and burn rates
  (`cb_slo_burn_rate{objective}`: fraction of window samples over the
  objective divided by the quantile's error budget — 1.0 = burning
  the budget exactly), and the composed **`cb_saturation`** signal.

Saturation is the scale signal ROADMAP item 4's router consumes: the
max of normalized pressure components (`cb_saturation_component`) —
busy-slot fraction, queue depth, queue-depth TREND over the window,
and paged-pool occupancy (1 - free+parked headroom). Max, not mean:
one exhausted resource is enough to need another slice, however idle
the others look.

All clocks are CALLER-supplied monotonic reads (the engine's own),
like `obs/trace.py` — deterministic under test, and windowed values
agree with the engine's record-derived ones by construction.
"""

from __future__ import annotations

import math
from collections import deque

__all__ = ["BucketRing", "SloTracker", "SATURATION_SIGNALS"]

# Every value the `signal` label can take, in documentation order.
SATURATION_SIGNALS = ("busy", "queue", "queue_trend", "pool")

# Objective key -> (window name, quantile). The error budget of a
# q-quantile objective is (1 - q): samples allowed over the threshold.
OBJECTIVES = {
    "ttft_p99_s": ("ttft", 0.99),
    "tpot_p99_s": ("tpot", 0.99),
}


class BucketRing:
    """Ring-of-buckets windowed view over one cumulative Histogram."""

    def __init__(self, hist, *, window_s: float = 30.0, buckets: int = 15):
        if window_s <= 0 or buckets <= 0:
            raise ValueError(
                f"need window_s > 0 and buckets > 0; got "
                f"{window_s}, {buckets}"
            )
        self._hist = hist
        self.window_s = float(window_s)
        self.bucket_s = self.window_s / buckets
        # (t, cumulative per-bucket counts, cumulative total) — newest
        # last; the head doubles as the window baseline once old
        # enough.
        self._snaps: deque[tuple] = deque()
        self._start_t: float | None = None
        self._last_advance: float | None = None

    @property
    def bounds(self):
        return self._hist.bounds

    def advance(self, now: float) -> None:
        """Rotate the ring: snapshot the cumulative counts when a
        bucket interval has passed, expire snapshots that fell out of
        the window (keeping the newest too-old one as the baseline).
        O(1) amortized; between boundaries it is one float compare."""
        if self._start_t is None:
            self._start_t = now
        self._last_advance = now
        if (
            not self._snaps
            or now - self._snaps[-1][0] >= self.bucket_s
        ):
            counts, total = self._hist.snapshot_counts()
            self._snaps.append((now, counts, total))
        cutoff = now - self.window_s
        while len(self._snaps) >= 2 and self._snaps[1][0] <= cutoff:
            self._snaps.popleft()

    def window_counts(self, now: float) -> tuple[list[int], int, float]:
        """(per-bucket counts, total, span_s) of the samples inside
        the trailing window: live counts minus the baseline snapshot
        — the NEWEST snapshot at or before the window cutoff, scanned
        here rather than relying on `advance()`'s expiry, because
        reads are wall-clock probes while rotation only happens on
        dispatch: an engine idle past the window must read EMPTY, not
        replay its last burst forever (samples can only land at
        dispatches, which rotate the ring, so the baseline is never
        staler than one bucket interval behind the cutoff). When NO
        rotation happened inside the window at all, the window is
        empty by construction — samples only land at dispatches, and
        every dispatch advances the ring — which also covers samples
        recorded after the final pre-idle snapshot. Before a full
        window has elapsed the span is the PARTIAL time since start
        (baseline zero)."""
        if (
            self._last_advance is not None
            and now - self._last_advance > self.window_s
        ):
            return [0] * len(self._hist.bounds), 0, self.window_s
        counts, total = self._hist.snapshot_counts()
        cutoff = now - self.window_s
        for t, base_counts, base_total in reversed(self._snaps):
            if t <= cutoff:
                delta = [
                    c - b for c, b in zip(counts, base_counts)
                ]
                return delta, total - base_total, self.window_s
        start = self._start_t if self._start_t is not None else now
        return counts, total, max(0.0, min(now - start, self.window_s))

    def quantile(self, q: float, now: float) -> float | None:
        """Nearest-rank quantile over the window — upper bound of the
        sample's bucket (one-bucket-width accuracy, +Inf overflow
        clamped to the last finite bound, both as the cumulative
        `Histogram.quantile` does). None on an empty window — "no
        samples" must never read as "zero latency"."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1]; got {q}")
        delta, total, _ = self.window_counts(now)
        if total <= 0:
            return None
        rank = max(1, math.ceil(q * total))
        cum = 0
        for bound, c in zip(self._hist.bounds, delta):
            cum += c
            if cum >= rank:
                return bound
        return self._hist.bounds[-1]

    def frac_over(self, threshold: float, now: float) -> float | None:
        """Fraction of window samples ABOVE `threshold` (bucket
        resolution: a sample counts as within-threshold iff its
        bucket's upper bound is <= threshold). None on an empty
        window."""
        delta, total, _ = self.window_counts(now)
        if total <= 0:
            return None
        ok = sum(
            c for bound, c in zip(self._hist.bounds, delta)
            if bound <= threshold
        )
        return max(0.0, (total - ok) / total)


class SloTracker:
    """Windowed SLO + saturation layer over a `ServingObs` bundle.

    `objectives` maps objective keys (see `OBJECTIVES`) to threshold
    seconds; unset objectives produce no `cb_slo_ok`/burn series and
    leave overall `ok` vacuously True once refreshed. The engine calls
    `on_sync` at every dispatch sync; gauge refresh is throttled to
    `refresh_s` so the per-sync cost stays at ring rotation.
    """

    def __init__(
        self,
        obs,
        *,
        slots: int,
        window_s: float = 30.0,
        buckets: int = 15,
        objectives: dict | None = None,
        refresh_s: float = 1.0,
    ):
        self.enabled = obs.enabled
        self._obs = obs
        self.window_s = float(window_s)
        self.refresh_s = float(refresh_s)
        self.objectives = {
            k: float(v)
            for k, v in (objectives or {}).items()
            if v is not None
        }
        unknown = set(self.objectives) - set(OBJECTIVES)
        if unknown:
            raise ValueError(
                f"unknown SLO objective(s) {sorted(unknown)}; "
                f"known: {sorted(OBJECTIVES)}"
            )
        self._slots = max(1, slots)
        self._rings = {
            "ttft": BucketRing(
                obs.ttft, window_s=window_s, buckets=buckets
            ),
            "tpot": BucketRing(
                obs.tpot, window_s=window_s, buckets=buckets
            ),
            "dispatch": BucketRing(
                obs.dispatch_latency, window_s=window_s, buckets=buckets
            ),
        }
        self._queue_samples: deque[tuple] = deque()
        self._last_refresh: float | None = None
        self._saturation: float | None = None
        self._components: dict = {
            s: None for s in SATURATION_SIGNALS
        }
        self._ok: bool | None = None
        self._ok_by: dict = {k: None for k in self.objectives}
        self._burn: dict = {k: None for k in self.objectives}

    # -- recording (engine driver thread) ------------------------------

    def on_sync(
        self,
        now: float,
        *,
        queue_depth: int,
        busy_slots: int,
        headroom_frac: float | None,
    ) -> None:
        """Per-dispatch hook at the host sync. `headroom_frac` is the
        paged pool's reclaimable fraction ((free + parked) /
        allocatable), None for the dense engine."""
        if not self.enabled:
            return
        for ring in self._rings.values():
            ring.advance(now)
        q = self._queue_samples
        q.append((now, queue_depth))
        cutoff = now - self.window_s
        while len(q) >= 2 and q[1][0] <= cutoff:
            q.popleft()
        if (
            self._last_refresh is not None
            and now - self._last_refresh < self.refresh_s
        ):
            return
        self._last_refresh = now
        self._refresh(now, queue_depth, busy_slots, headroom_frac)

    def _compliance(self, now: float) -> tuple[dict, dict]:
        """(ok_by_objective, burn_by_objective) over the current
        window. A window with no samples yields None for both — no
        evidence of breach; compliance unknown, never "violated by
        silence"."""
        ok_by: dict = {}
        burn_by: dict = {}
        for key, threshold in self.objectives.items():
            window, q = OBJECTIVES[key]
            over = self._rings[window].frac_over(threshold, now)
            if over is None:
                ok_by[key] = None
                burn_by[key] = None
                continue
            budget = 1.0 - q
            ok_by[key] = over <= budget
            burn_by[key] = round(over / budget, 4)
        return ok_by, burn_by

    def ok_at(self, now: float) -> bool | None:
        """Overall compliance computed LIVE over the current window
        (the `/healthz` `slo_ok` field): False iff any configured
        objective measurably breached its budget; None before the
        first dispatch or with telemetry off. Live, not last-refresh:
        a short request burst can end inside one refresh interval,
        and the probe must still see its breaches."""
        if not self.enabled or self._last_refresh is None:
            return None
        ok_by, _ = self._compliance(now)
        return not any(v is False for v in ok_by.values())

    def _refresh(
        self, now, queue_depth, busy_slots, headroom_frac
    ) -> None:
        obs = self._obs
        ttft_p50 = self._rings["ttft"].quantile(0.50, now)
        ttft_p99 = self._rings["ttft"].quantile(0.99, now)
        tpot_p99 = self._rings["tpot"].quantile(0.99, now)
        disp_p99 = self._rings["dispatch"].quantile(0.99, now)
        for gauge, value in (
            (obs.slo_ttft_p50, ttft_p50),
            (obs.slo_ttft_p99, ttft_p99),
            (obs.slo_tpot_p99, tpot_p99),
            (obs.slo_dispatch_p99, disp_p99),
        ):
            if value is not None:  # empty window: leave unset, not 0
                gauge.set(value)
        self._ok_by, self._burn = self._compliance(now)
        for key, ok in self._ok_by.items():
            if ok is None:
                continue
            obs.slo_ok_gauge.set(
                1.0 if ok else 0.0, labels={"objective": key}
            )
            obs.slo_burn.set(
                self._burn[key], labels={"objective": key}
            )
        # Overall compliance: any measured breach flips it; unknowns
        # don't (an idle engine is not out of SLO).
        self._ok = not any(v is False for v in self._ok_by.values())
        # Saturation components, each normalized to [0, 1].
        depth0 = self._queue_samples[0][1] if self._queue_samples else 0
        components = {
            "busy": min(1.0, busy_slots / self._slots),
            "queue": min(1.0, queue_depth / (2.0 * self._slots)),
            "queue_trend": min(
                1.0, max(0.0, (queue_depth - depth0) / self._slots)
            ),
            "pool": (
                None if headroom_frac is None
                else min(1.0, max(0.0, 1.0 - headroom_frac))
            ),
        }
        self._components = {
            k: None if v is None else round(v, 4)
            for k, v in components.items()
        }
        present = [v for v in components.values() if v is not None]
        self._saturation = round(max(present), 4) if present else None
        for signal, value in self._components.items():
            if value is not None:
                obs.saturation_component.set(
                    value, labels={"signal": signal}
                )
        if self._saturation is not None:
            obs.saturation.set(self._saturation)

    # -- reading (any thread) ------------------------------------------

    @property
    def saturation(self) -> float | None:
        """Composed scale signal from the last refresh (None before
        the first dispatch, or with telemetry off)."""
        return self._saturation

    @property
    def ok(self) -> bool | None:
        """Overall SLO compliance from the last refresh: False iff a
        configured objective measurably breached its budget."""
        return self._ok

    def stats(self, now: float) -> dict:
        """Windowed-SLO view — the `/debug/slo` payload and the
        `/stats` `cb_slo` section. Quantiles AND compliance/burn are
        computed live at call time over the current window (the
        gauges refresh throttled; a reader must never see staler
        compliance than the window it is shown beside); saturation is
        the last refresh's (its inputs are sync-time engine state).
        Same dict shape with telemetry off, flagged `obs_disabled`
        (the PR 3 convention)."""
        windows = {}
        for name, ring in self._rings.items():
            if self.enabled:
                _, total, span = ring.window_counts(now)
                windows[name] = {
                    "count": total,
                    "p50": ring.quantile(0.50, now),
                    "p99": ring.quantile(0.99, now),
                    "span_s": round(span, 3),
                }
            else:
                windows[name] = {
                    "count": 0, "p50": None, "p99": None,
                    "span_s": 0.0,
                }
        if self.enabled:
            ok_by, burn_by = self._compliance(now)
            # Overall bit derived from the map already in hand (one
            # _compliance pass per read, and no second code path for
            # ok_at to drift from).
            overall = (
                None if self._last_refresh is None
                else not any(v is False for v in ok_by.values())
            )
        else:
            ok_by = {k: None for k in self.objectives}
            burn_by = {k: None for k in self.objectives}
            overall = None
        return {
            **({} if self.enabled else {"obs_disabled": True}),
            "window_s": self.window_s,
            "objectives": dict(self.objectives),
            "windows": windows,
            "slo_ok": ok_by,
            "ok": overall,
            "burn_rate": burn_by,
            "saturation": {
                "value": self._saturation,
                "components": dict(self._components),
            },
        }

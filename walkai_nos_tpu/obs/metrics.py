"""Unified metrics registry: counters, gauges, log-bucketed histograms.

ONE metrics implementation for every surface this repo exposes — the
serving engine's dispatch-loop telemetry (`models/serve.py` via
`obs/serving.py`), the kube binaries' controller metrics
(`health.Metrics` is now a thin adapter over this `Registry`), and the
install exporter's node-inventory gauges (`cmd/metricsexporter.py`).
Before this module each of those hand-rolled its own counters and its
own exposition; the names could drift and nothing machine-scrapeable
existed on the serving side at all.

Design constraints, in order:

- **Off the critical path.** Instrument writes happen on the host in
  the serving engine's dispatch loop, between device dispatches that
  take milliseconds; a write is a dict update under one lock
  (sub-microsecond). The registry can also be constructed
  `enabled=False`, turning every write into an attribute check — the
  A/B the bench's `obs_overhead_pct` headline key measures.
- **Stdlib only.** No prometheus_client dependency: the kube images
  and the serving container share one zero-dependency implementation,
  and `hack/metrics_lint.py` can import the catalog without jax.
- **Prometheus text exposition** (`Registry.render`): the 0.0.4 text
  format, with label-value escaping so one hostile value cannot
  corrupt the payload, and the full histogram contract (cumulative
  `_bucket{le=...}` series, `+Inf`, `_sum`, `_count`).

Histograms are log-bucketed (`log_buckets`): serving latencies span
~four decades (sub-ms chunk syncs to 100 s stragglers), so geometric
bucket spacing gives constant RELATIVE resolution — every estimate is
exact to within one bucket width, which is the tolerance the bench
parity check (`tests/test_obs.py`) pins.
"""

from __future__ import annotations

import math
import threading

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "escape_label",
    "log_buckets",
]


def escape_label(value) -> str:
    """Prometheus exposition label escaping: one bad value (a quote or
    newline from an object name or error string) must not corrupt the
    whole /metrics payload."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def log_buckets(
    lo: float, hi: float, per_decade: int = 3
) -> tuple[float, ...]:
    """Geometric bucket upper bounds from `lo` until `hi` is covered:
    `per_decade` bounds per power of ten, so resolution is a constant
    RATIO (10^(1/per_decade), ~2.15x at the default) across the whole
    range — the right shape for latencies spanning decades."""
    if not (lo > 0 and hi > lo and per_decade > 0):
        raise ValueError(
            f"need 0 < lo < hi and per_decade > 0; "
            f"got {lo}, {hi}, {per_decade}"
        )
    bounds = []
    exp = math.log10(lo)
    step = 1.0 / per_decade
    while True:
        b = 10.0 ** exp
        # Snap to a clean decimal (10^k x {1, 2.15, 4.64} style values
        # print horribly); round to 4 significant digits instead.
        b = float(f"{b:.4g}")
        bounds.append(b)
        if b >= hi:
            return tuple(bounds)
        exp += step


# Serving latencies: 1 ms resolution floor, 100 s ceiling (the demo
# server's request timeout is 120 s; anything slower lands in +Inf).
DEFAULT_TIME_BUCKETS = log_buckets(1e-3, 100.0)


def _fmt(value: float) -> str:
    """Render a sample value: integral floats print as integers (the
    common case for counters), everything else as repr (full float
    precision; Prometheus parsers accept Go float syntax). Non-finite
    values use the format's own spellings — a gauge someone set to
    inf/NaN must not take down the whole exposition."""
    if not math.isfinite(value):
        if value != value:
            return "NaN"
        return "+Inf" if value > 0 else "-Inf"
    if value == int(value) and abs(value) < 2**53:
        return str(int(value))
    return repr(value)


class _Metric:
    """Base: a named instrument registered in one `Registry`. Series
    (per-label-set values) live here, guarded by the registry lock."""

    kind = "untyped"

    def __init__(self, registry: "Registry", name: str, help_text: str):
        self._registry = registry
        self._lock = registry._lock
        self._enabled = registry.enabled
        self.name = name
        self.help = help_text
        self._series: dict[tuple, object] = {}

    @staticmethod
    def _key(labels: dict | None) -> tuple:
        return tuple(sorted((labels or {}).items()))

    def labelsets(self) -> list[dict]:
        with self._lock:
            return [dict(k) for k in self._series]

    def remove(self, labels: dict | None = None) -> None:
        """Drop one label set's series entirely, so exposition stops
        exporting it. For series whose label values name transient
        members (a fleet replica that was retired): keeping the last
        value exports a dead member as live forever, and 0 would read
        as 'observed idle', not 'gone'."""
        with self._lock:
            self._series.pop(self._key(labels), None)


class Counter(_Metric):
    """Monotonically increasing sum. Name should end in `_total` (or
    `_sum` for cumulative seconds), per Prometheus convention."""

    kind = "counter"

    def inc(self, value: float = 1.0, labels: dict | None = None) -> None:
        if not self._enabled:
            return
        if value < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value

    def value(self, labels: dict | None = None) -> float:
        with self._lock:
            return float(self._series.get(self._key(labels), 0.0))


class Gauge(_Metric):
    """A value that goes up and down. `value()` is None until the
    first `set` — "never observed" and "observed 0" are different
    answers for snapshot-style consumers (`kv_stats`)."""

    kind = "gauge"

    def set(self, value: float, labels: dict | None = None) -> None:
        if not self._enabled:
            return
        with self._lock:
            self._series[self._key(labels)] = float(value)

    def set_min(self, value: float, labels: dict | None = None) -> None:
        """Keep the smallest value ever set — a low watermark (the
        block pool's worst-case headroom under load)."""
        if not self._enabled:
            return
        key = self._key(labels)
        with self._lock:
            prev = self._series.get(key)
            if prev is None or value < prev:
                self._series[key] = float(value)

    def value(self, labels: dict | None = None) -> float | None:
        with self._lock:
            v = self._series.get(self._key(labels))
            return None if v is None else float(v)


class _HistState:
    __slots__ = ("counts", "total", "sum")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets  # per-bucket (non-cumulative)
        self.total = 0  # includes the +Inf overflow
        self.sum = 0.0


class Histogram(_Metric):
    """Log-bucketed histogram. A sample lands in the first bucket
    whose upper bound is >= the value (Prometheus `le` semantics:
    bounds are INCLUSIVE upper edges); values above the last bound
    count only toward `+Inf`/`_count`/`_sum`."""

    kind = "histogram"

    def __init__(
        self,
        registry: "Registry",
        name: str,
        help_text: str,
        buckets: tuple[float, ...] | None = None,
    ):
        super().__init__(registry, name, help_text)
        bounds = tuple(buckets or DEFAULT_TIME_BUCKETS)
        if list(bounds) != sorted(set(bounds)):
            raise ValueError(
                f"histogram {name} buckets must be strictly increasing"
            )
        self.bounds = bounds

    def observe(self, value: float, labels: dict | None = None) -> None:
        if not self._enabled:
            return
        key = self._key(labels)
        with self._lock:
            state = self._series.get(key)
            if state is None:
                state = self._series[key] = _HistState(len(self.bounds))
            # Linear scan: bucket counts are small (~20) and the scan
            # usually exits in the first few bounds for sub-second
            # latencies; bisect would allocate a key tuple per call.
            for i, b in enumerate(self.bounds):
                if value <= b:
                    state.counts[i] += 1
                    break
            state.total += 1
            state.sum += value

    def count(self, labels: dict | None = None) -> int:
        with self._lock:
            state = self._series.get(self._key(labels))
            return 0 if state is None else state.total

    def snapshot_counts(
        self, labels: dict | None = None
    ) -> tuple[list[int], int]:
        """(per-bucket counts copy, total incl. the +Inf overflow) —
        the raw material the sliding-window SLO layer (`obs/slo.py`)
        snapshots into its ring of buckets: two snapshots differenced
        give the bucket counts of exactly the samples between them."""
        with self._lock:
            state = self._series.get(self._key(labels))
            if state is None:
                return [0] * len(self.bounds), 0
            return list(state.counts), state.total

    def sum(self, labels: dict | None = None) -> float:
        with self._lock:
            state = self._series.get(self._key(labels))
            return 0.0 if state is None else state.sum

    def quantile(self, q: float, labels: dict | None = None) -> float | None:
        """Upper bound of the bucket containing the q-quantile (q in
        [0, 1]) — exact to within one bucket width, which is the
        guarantee the bench parity test leans on. Samples in the +Inf
        overflow report the last finite bound (Prometheus
        `histogram_quantile` clamps the same way). None until any
        sample lands."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1]; got {q}")
        with self._lock:
            state = self._series.get(self._key(labels))
            if state is None or state.total == 0:
                return None
            # Nearest-rank on the cumulative counts.
            rank = max(1, math.ceil(q * state.total))
            cum = 0
            for i, c in enumerate(state.counts):
                cum += c
                if cum >= rank:
                    return self.bounds[i]
            return self.bounds[-1]


class Registry:
    """Named instruments + Prometheus text exposition.

    `counter/gauge/histogram` are get-or-create: the first call fixes
    the kind and help text (re-registration with a different kind is a
    programming error and raises). `enabled=False` builds a registry
    whose instruments no-op on write — the disabled arm of the
    `obs_overhead_pct` A/B."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help_text: str, **kw):
        # Create under the lock: two threads racing the same name must
        # never each see "absent" and hand one of them an instrument
        # of the other's kind (instrument __init__ only assigns
        # attributes — no lock re-entry, no I/O).
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = cls(self, name, help_text, **kw)
                self._metrics[name] = metric
        if not isinstance(metric, cls):
            raise ValueError(
                f"metric {name} already registered as "
                f"{metric.kind}, not {cls.kind}"
            )
        return metric

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._get_or_create(Counter, name, help_text)

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help_text)

    def histogram(
        self,
        name: str,
        help_text: str = "",
        buckets: tuple[float, ...] | None = None,
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help_text, buckets=buckets
        )

    def get(self, name: str) -> _Metric | None:
        with self._lock:
            return self._metrics.get(name)

    def describe(self) -> dict[str, tuple[str, str]]:
        """name -> (kind, help) for every registered instrument."""
        with self._lock:
            return {
                name: (m.kind, m.help)
                for name, m in sorted(self._metrics.items())
            }

    def render(self) -> str:
        """Prometheus text exposition (format 0.0.4)."""
        lines: list[str] = []
        with self._lock:
            metrics = sorted(self._metrics.items())
        for name, metric in metrics:
            with self._lock:
                series = sorted(
                    self._series_snapshot(metric).items()
                )
            if not series:
                continue
            if metric.help:
                lines.append(f"# HELP {name} {metric.help}")
            lines.append(f"# TYPE {name} {metric.kind}")
            for key, value in series:
                if isinstance(metric, Histogram):
                    self._render_histogram(
                        lines, name, metric, key, value
                    )
                else:
                    lines.append(
                        f"{name}{self._labels(key)} {_fmt(value)}"
                    )
        return "\n".join(lines) + "\n"

    @staticmethod
    def _series_snapshot(metric: _Metric) -> dict:
        # Caller holds the lock; histograms copy their mutable state.
        if isinstance(metric, Histogram):
            out = {}
            for key, st in metric._series.items():
                copy = _HistState(len(st.counts))
                copy.counts = list(st.counts)
                copy.total, copy.sum = st.total, st.sum
                out[key] = copy
            return out
        return dict(metric._series)

    @staticmethod
    def _labels(key: tuple, extra: str = "") -> str:
        parts = [f'{k}="{escape_label(v)}"' for k, v in key]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    @classmethod
    def _render_histogram(
        cls, lines: list, name: str, metric: Histogram, key: tuple,
        state: _HistState,
    ) -> None:
        cum = 0
        for bound, count in zip(metric.bounds, state.counts):
            cum += count
            le = 'le="' + _fmt(bound) + '"'
            lines.append(f"{name}_bucket{cls._labels(key, le)} {cum}")
        inf = 'le="+Inf"'
        lines.append(
            f"{name}_bucket{cls._labels(key, inf)} {state.total}"
        )
        lines.append(f"{name}_sum{cls._labels(key)} {_fmt(state.sum)}")
        lines.append(f"{name}_count{cls._labels(key)} {state.total}")

"""Shared watch streams: one upstream watch per kind, many consumers.

The reference's controller-runtime manager backs every controller with
a shared informer cache — one API-server watch per kind regardless of
how many controllers consume it. Our `Controller` opens its own watch,
which is fine for single-watch binaries but duplicates streams where
one process runs several controllers over the same kind (the scheduler
binary watches Pods for scheduling AND capacity labeling). This
decorator restores the informer property: the first `watch(kind, ns)`
starts one upstream stream + a pump thread; later subscribers replay
the current cache as synthetic ADDED…SYNCED and then ride the same
stream. Everything else delegates to the wrapped client.

Reference: controller-runtime's shared cache
(`cmd/gpupartitioner/gpupartitioner.go:49` builds every controller on
one manager; SURVEY.md §2.12).
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Callable, Iterator

from walkai_nos_tpu.kube.client import RESYNC, SYNCED, KubeClient, WatchEvent

logger = logging.getLogger(__name__)

_SENTINEL = object()

# A subscriber whose queue backs up past this many undelivered events is
# evicted (its iterator ends with the sentinel). A live Controller drains
# its queue every loop, so only an abandoned iterator — one the consumer
# dropped without closing, leaving the generator (and its queue) alive
# until GC — accumulates unboundedly. Eviction caps that leak; a consumer
# that was merely slow re-subscribes and gets a fresh replay, which is
# exactly the informer re-list contract it already handles.
MAX_SUBSCRIBER_BACKLOG = 4096


class _Stream:
    """One upstream watch for a (kind, namespace) key."""

    def __init__(self, client: KubeClient, kind: str, namespace):
        self._client = client
        self._kind = kind
        self._namespace = namespace
        self._lock = threading.Lock()
        self._synced_cv = threading.Condition(self._lock)
        self._cache: dict[tuple[str, str], dict] = {}
        self._resync_seen: set = set()
        # True once the stream reached a consistent point (initial
        # ADDED…SYNCED complete, and not inside a RESYNC replay window).
        self._synced = False
        self._subscribers: list[queue.SimpleQueue] = []
        self._stopped = False
        self._thread = threading.Thread(
            target=self._pump, name=f"sharedwatch-{kind}", daemon=True
        )
        self._started = False

    # ------------------------------------------------------------- upstream

    def _pump(self) -> None:
        try:
            for event, obj in self._client.watch(
                self._kind, self._namespace, stop=lambda: self._stopped
            ):
                with self._lock:
                    self._apply(event, obj)
                    targets = list(self._subscribers)
                for q in targets:
                    if q.qsize() >= MAX_SUBSCRIBER_BACKLOG:
                        with self._lock:
                            if q in self._subscribers:
                                self._subscribers.remove(q)
                        q.put(_SENTINEL)
                        logger.warning(
                            "sharedwatch %s: evicted a subscriber with "
                            ">= %d undelivered events (abandoned or "
                            "stalled iterator)",
                            self._kind, MAX_SUBSCRIBER_BACKLOG,
                        )
                        continue
                    q.put((event, obj))
        except Exception:
            logger.exception(
                "shared watch for %s died; subscribers unblocked",
                self._kind,
            )
        finally:
            with self._lock:
                self._stopped = True
                targets = list(self._subscribers)
                self._synced_cv.notify_all()
            for q in targets:
                q.put(_SENTINEL)

    def _apply(self, event: str, obj: dict) -> None:
        """Mirror the upstream protocol into the replay cache. During a
        RESYNC replay the stream re-mentions every survivor, so drop
        what the replay didn't re-mention at its SYNCED (same semantics
        Controller applies to its own cache)."""
        if event == RESYNC:
            self._resync_seen = set(self._cache)
            self._synced = False
            return
        if event == SYNCED:
            for key in self._resync_seen:
                self._cache.pop(key, None)
            self._resync_seen = set()
            self._synced = True
            self._synced_cv.notify_all()
            return
        meta = obj.get("metadata", {})
        key = (meta.get("namespace", ""), meta.get("name", ""))
        if event == "DELETED":
            self._cache.pop(key, None)
        else:
            self._cache[key] = obj
            self._resync_seen.discard(key)

    # ----------------------------------------------------------- subscribers

    def subscribe(
        self, stop: Callable[[], bool]
    ) -> Iterator[WatchEvent]:
        """Yield the informer's state as the standard ADDED…SYNCED
        framing, then live events. Joins wait for the stream to reach a
        consistent point first — snapshotting mid-burst or mid-RESYNC
        would hand the joiner a partial or stale world whose missing
        objects its Controller would treat as deletions (or ghosts).

        Close the iterator when done (`with closing(...)` or exhaust
        it); an abandoned-but-alive generator keeps its queue
        registered until GC, and is evicted once its backlog exceeds
        MAX_SUBSCRIBER_BACKLOG."""
        q: queue.SimpleQueue = queue.SimpleQueue()
        with self._lock:
            if not self._started:
                self._started = True
                self._thread.start()
            while not self._synced and not self._stopped:
                if stop():
                    return
                self._synced_cv.wait(timeout=0.2)
            snapshot = list(self._cache.values())
            dead = self._stopped
            if not dead:
                self._subscribers.append(q)
        try:
            for obj in snapshot:
                yield ("ADDED", obj)
            # Always close the initial burst — an empty SYNCED is what
            # lets a re-subscribing Controller prune its stale cache
            # (the upstream watch contract, client.py).
            yield (SYNCED, {})
            if dead:
                return
            while not stop():
                try:
                    item = q.get(timeout=0.2)
                except queue.Empty:
                    continue
                if item is _SENTINEL:
                    return
                yield item
        finally:
            with self._lock:
                if q in self._subscribers:
                    self._subscribers.remove(q)

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            self._synced_cv.notify_all()


class SharedWatchClient(KubeClient):
    """KubeClient decorator multiplexing watches per (kind, namespace)."""

    def __init__(self, client: KubeClient):
        self._client = client
        self._streams: dict[tuple[str, str | None], _Stream] = {}
        self._lock = threading.Lock()

    # --------------------------------------------------------------- watch

    def watch(
        self,
        kind: str,
        namespace: str | None = None,
        stop: Callable[[], bool] | None = None,
    ) -> Iterator[WatchEvent]:
        stop = stop or (lambda: False)
        key = (kind, namespace)
        with self._lock:
            stream = self._streams.get(key)
            if stream is None or stream._stopped:
                stream = _Stream(self._client, kind, namespace)
                self._streams[key] = stream
        return stream.subscribe(stop)

    def close(self) -> None:
        with self._lock:
            for stream in self._streams.values():
                stream.stop()

    # ------------------------------------------------------------ delegates

    def get(self, kind, name, namespace=None):
        return self._client.get(kind, name, namespace)

    def list(self, kind, namespace=None, label_selector=None,
             field_selector=None):
        return self._client.list(
            kind, namespace, label_selector, field_selector
        )

    def create(self, kind, obj, namespace=None):
        return self._client.create(kind, obj, namespace)

    def update(self, kind, obj, namespace=None):
        return self._client.update(kind, obj, namespace)

    def patch(self, kind, name, patch, namespace=None):
        return self._client.patch(kind, name, patch, namespace)

    def patch_status(self, kind, name, patch, namespace=None):
        return self._client.patch_status(kind, name, patch, namespace)

    def delete(self, kind, name, namespace=None):
        return self._client.delete(kind, name, namespace)

    def bind_pod(self, name, namespace, node_name):
        return self._client.bind_pod(name, namespace, node_name)

    def evict_pod(self, name, namespace, grace_period_seconds=None):
        return self._client.evict_pod(name, namespace, grace_period_seconds)

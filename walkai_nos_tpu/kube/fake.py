"""In-memory fake API server — the envtest analogue.

The reference's integration suites boot a real kube-apiserver via envtest
(`suite_int_test.go:33-163`); binaries aren't shippable here, so this fake
implements the subset the controllers rely on — CRUD, JSON merge patch,
label/field selectors, resourceVersion conflict detection, and fan-out
watches — behind the same `KubeClient` interface, thread-safe.
"""

from __future__ import annotations

import itertools
import queue
import threading
import uuid
from typing import Callable, Iterator, Mapping

from walkai_nos_tpu.kube import objects
from walkai_nos_tpu.kube.client import (
    SYNCED,
    Conflict,
    KubeClient,
    NotFound,
    WatchEvent,
)

_CLUSTER_SCOPED = {"Node", "Namespace", "ElasticQuota" }


def _key(kind: str, name: str, namespace: str | None) -> tuple:
    if kind in _CLUSTER_SCOPED:
        return (kind, "", name)
    return (kind, namespace or "default", name)


class FakeKubeClient(KubeClient):
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._objects: dict[tuple, dict] = {}
        self._watchers: dict[str, list[queue.Queue]] = {}
        self._rv = itertools.count(1)
        # (name, namespace, grace_period_seconds) per successful eviction.
        self.evictions: list[tuple[str, str, int | None]] = []

    # ------------------------------------------------------------------ CRUD

    def get(self, kind: str, name: str, namespace: str | None = None) -> dict:
        with self._lock:
            obj = self._objects.get(_key(kind, name, namespace))
            if obj is None:
                raise NotFound(f"{kind} {namespace or ''}/{name}")
            return objects.deep_copy(obj)

    def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: Mapping[str, str] | None = None,
        field_selector: Mapping[str, str] | None = None,
    ) -> list[dict]:
        with self._lock:
            out = []
            for (k, ns, _), obj in sorted(self._objects.items()):
                if k != kind:
                    continue
                if (
                    namespace is not None
                    and kind not in _CLUSTER_SCOPED
                    and ns != namespace
                ):
                    continue
                if label_selector and not objects.matches_labels(
                    obj, label_selector
                ):
                    continue
                if field_selector and not _matches_fields(obj, field_selector):
                    continue
                out.append(objects.deep_copy(obj))
            return out

    def create(self, kind: str, obj: dict, namespace: str | None = None) -> dict:
        with self._lock:
            obj = objects.deep_copy(obj)
            meta = obj.setdefault("metadata", {})
            if namespace and kind not in _CLUSTER_SCOPED:
                meta.setdefault("namespace", namespace)
            key = _key(kind, meta.get("name", ""), meta.get("namespace"))
            if not meta.get("name"):
                raise ValueError("metadata.name required")
            if key in self._objects:
                raise Conflict(f"{kind} {meta.get('name')} already exists")
            meta.setdefault("uid", str(uuid.uuid4()))
            meta["resourceVersion"] = str(next(self._rv))
            obj.setdefault("kind", kind)
            self._objects[key] = obj
            self._notify(kind, ("ADDED", objects.deep_copy(obj)))
            return objects.deep_copy(obj)

    def update(self, kind: str, obj: dict, namespace: str | None = None) -> dict:
        with self._lock:
            obj = objects.deep_copy(obj)
            meta = obj.setdefault("metadata", {})
            key = _key(kind, meta.get("name", ""), meta.get("namespace") or namespace)
            existing = self._objects.get(key)
            if existing is None:
                raise NotFound(f"{kind} {meta.get('name')}")
            sent_rv = meta.get("resourceVersion")
            if sent_rv and sent_rv != existing["metadata"]["resourceVersion"]:
                raise Conflict(
                    f"{kind} {meta.get('name')}: stale resourceVersion"
                )
            meta["uid"] = existing["metadata"]["uid"]
            meta["resourceVersion"] = str(next(self._rv))
            self._objects[key] = obj
            self._notify(kind, ("MODIFIED", objects.deep_copy(obj)))
            return objects.deep_copy(obj)

    def patch(
        self,
        kind: str,
        name: str,
        patch: dict,
        namespace: str | None = None,
    ) -> dict:
        with self._lock:
            key = _key(kind, name, namespace)
            existing = self._objects.get(key)
            if existing is None:
                raise NotFound(f"{kind} {namespace or ''}/{name}")
            merged = objects.merge_patch(existing, patch)
            # identity fields are immutable
            merged.setdefault("metadata", {})["name"] = name
            merged["metadata"]["uid"] = existing["metadata"]["uid"]
            merged["metadata"]["resourceVersion"] = str(next(self._rv))
            if existing["metadata"].get("namespace"):
                merged["metadata"]["namespace"] = existing["metadata"]["namespace"]
            self._objects[key] = merged
            self._notify(kind, ("MODIFIED", objects.deep_copy(merged)))
            return objects.deep_copy(merged)

    def delete(self, kind: str, name: str, namespace: str | None = None) -> None:
        with self._lock:
            key = _key(kind, name, namespace)
            obj = self._objects.pop(key, None)
            if obj is None:
                raise NotFound(f"{kind} {namespace or ''}/{name}")
            self._notify(kind, ("DELETED", objects.deep_copy(obj)))

    def evict_pod(
        self,
        name: str,
        namespace: str,
        grace_period_seconds: int | None = None,
    ) -> None:
        """pods/eviction emulation: enforce PodDisruptionBudgets the way
        the real subresource handler does (`kube.disruption`), then
        delete. Evictions are recorded (`self.evictions`) so tests can
        assert the grace period the caller granted."""
        from walkai_nos_tpu.kube.client import EvictionBlocked
        from walkai_nos_tpu.kube.disruption import eviction_allowed

        with self._lock:
            pod = self._objects.get(_key("Pod", name, namespace))
            if pod is None:
                raise NotFound(f"Pod {namespace}/{name}")
            pdbs = [
                objects.deep_copy(o)
                for (k, ns, _), o in self._objects.items()
                if k == "PodDisruptionBudget" and ns == namespace
            ]
            pods = [
                objects.deep_copy(o)
                for (k, ns, _), o in self._objects.items()
                if k == "Pod" and ns == namespace
            ]
            allowed, reason = eviction_allowed(pod, pdbs, pods)
            if not allowed:
                raise EvictionBlocked(reason)
            self.evictions.append((name, namespace, grace_period_seconds))
            self.delete("Pod", name, namespace)

    # ----------------------------------------------------------------- watch

    def watch(
        self,
        kind: str,
        namespace: str | None = None,
        stop: Callable[[], bool] | None = None,
    ) -> Iterator[WatchEvent]:
        # Register the watcher EAGERLY (at call time, not first iteration):
        # a lazy generator would open a race window between `watch(...)`
        # returning and the first `next()`, during which events are lost
        # and the backlog snapshot goes stale.
        q: queue.Queue = queue.Queue()
        with self._lock:
            backlog = [
                ("ADDED", o) for o in self.list(kind, namespace=namespace)
            ]
            backlog.append((SYNCED, {}))
            self._watchers.setdefault(kind, []).append(q)
        return self._watch_iter(kind, namespace, stop, q, backlog)

    def _watch_iter(
        self,
        kind: str,
        namespace: str | None,
        stop: Callable[[], bool] | None,
        q: "queue.Queue",
        backlog: list[WatchEvent],
    ) -> Iterator[WatchEvent]:
        try:
            for ev in backlog:
                yield ev
            while True:
                if stop and stop():
                    return
                try:
                    ev = q.get(timeout=0.05)
                except queue.Empty:
                    continue
                if namespace is not None and kind not in _CLUSTER_SCOPED:
                    if objects.namespace(ev[1]) != namespace:
                        continue
                yield ev
        finally:
            with self._lock:
                try:
                    self._watchers.get(kind, []).remove(q)
                except ValueError:
                    pass

    def _notify(self, kind: str, event: WatchEvent) -> None:
        for q in self._watchers.get(kind, []):
            q.put(event)


def _matches_fields(obj: Mapping, selector: Mapping[str, str]) -> bool:
    for path, want in selector.items():
        cur: object = obj
        for part in path.split("."):
            if not isinstance(cur, Mapping):
                cur = None
                break
            cur = cur.get(part)
        if cur != want:
            return False
    return True

"""RestKubeClient: the real API-server implementation of KubeClient.

Dependency-light (stdlib HTTP) Kubernetes REST client covering exactly what
the controllers need: CRUD + merge-patch + watch on the kinds this control
plane touches. Credential resolution mirrors client-go's in-cluster config
(`rest.InClusterConfig` — service-account token + CA from
/var/run/secrets/kubernetes.io/serviceaccount) with a KUBECONFIG fallback
for dev clusters (kind/minikube, cf. the reference's local flows,
`Makefile:115-117`, `docs/walkai/deploy.md`).

Watches use the streaming watch API with resourceVersion bookkeeping and
seed the stream with synthetic ADDED events from a fresh list — the same
informer-cache semantics `FakeKubeClient.watch` provides, so controllers
behave identically against either implementation.
"""

from __future__ import annotations

import base64
import json
import logging
import os
import ssl
import tempfile
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Iterator, Mapping

from walkai_nos_tpu.kube.client import (
    RESYNC,
    SYNCED,
    ApiError,
    Conflict,
    EvictionBlocked,
    KubeClient,
    NotFound,
    WatchEvent,
)

logger = logging.getLogger(__name__)

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# kind -> (api prefix, plural, namespaced)
_KINDS: dict[str, tuple[str, str, bool]] = {
    "Node": ("/api/v1", "nodes", False),
    "Pod": ("/api/v1", "pods", True),
    "Namespace": ("/api/v1", "namespaces", False),
    "ConfigMap": ("/api/v1", "configmaps", True),
    "Event": ("/api/v1", "events", True),
    "Lease": ("/apis/coordination.k8s.io/v1", "leases", True),
    "ResourceQuota": ("/api/v1", "resourcequotas", True),
    "PodDisruptionBudget": ("/apis/policy/v1", "poddisruptionbudgets", True),
    "ElasticQuota": ("/apis/nos.walkai.io/v1alpha1", "elasticquotas", True),
    "CompositeElasticQuota": (
        "/apis/nos.walkai.io/v1alpha1",
        "compositeelasticquotas",
        True,
    ),
}


def _kind_route(kind: str) -> tuple[str, str, bool]:
    try:
        return _KINDS[kind]
    except KeyError:
        raise ApiError(400, f"unknown kind {kind!r}") from None


class RestKubeClient(KubeClient):
    def __init__(
        self,
        server: str | None = None,
        token: str | None = None,
        ca_file: str | None = None,
        insecure: bool = False,
        timeout: float = 30.0,
        client_cert: tuple[str, str] | None = None,  # (cert_file, key_file)
    ) -> None:
        if server is None:
            server, token, ca_file, insecure, client_cert = (
                self._resolve_config()
            )
        self._server = server.rstrip("/")
        self._token = token
        self._timeout = timeout
        if insecure:
            self._ssl = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            self._ssl.check_hostname = False
            self._ssl.verify_mode = ssl.CERT_NONE
        elif ca_file:
            self._ssl = ssl.create_default_context(cafile=ca_file)
        else:
            self._ssl = ssl.create_default_context()
        if client_cert:
            # mTLS client auth — what kind/minikube kubeconfigs use.
            self._ssl.load_cert_chain(client_cert[0], client_cert[1])

    # -------------------------------------------------------------- config

    @staticmethod
    def _resolve_config():
        """In-cluster first, then $KUBECONFIG (current-context).

        Returns (server, token, ca_file, insecure, client_cert).
        """
        token_path = os.path.join(_SA_DIR, "token")
        if os.path.exists(token_path):
            host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            with open(token_path) as f:
                token = f.read().strip()
            ca = os.path.join(_SA_DIR, "ca.crt")
            return (
                f"https://{host}:{port}",
                token,
                ca if os.path.exists(ca) else None,
                False,
                None,
            )
        kubeconfig = os.environ.get(
            "KUBECONFIG", os.path.expanduser("~/.kube/config")
        )
        if os.path.exists(kubeconfig):
            return RestKubeClient._from_kubeconfig(kubeconfig)
        raise ApiError(500, "no in-cluster credentials and no kubeconfig")

    @staticmethod
    def _materialize(data_b64: str | None, path: str | None, suffix: str):
        """Inline base64 kubeconfig data -> temp file path."""
        if data_b64:
            fd, path = tempfile.mkstemp(suffix=suffix)
            with os.fdopen(fd, "wb") as f:
                f.write(base64.b64decode(data_b64))
        return path

    @staticmethod
    def _from_kubeconfig(path: str):
        import yaml

        with open(path) as f:
            cfg = yaml.safe_load(f)
        ctx_name = cfg.get("current-context")
        ctx = next(
            c["context"] for c in cfg["contexts"] if c["name"] == ctx_name
        )
        cluster = next(
            c["cluster"]
            for c in cfg["clusters"]
            if c["name"] == ctx["cluster"]
        )
        user = next(
            u["user"] for u in cfg["users"] if u["name"] == ctx["user"]
        )
        server = cluster["server"]
        insecure = bool(cluster.get("insecure-skip-tls-verify"))
        ca_file = RestKubeClient._materialize(
            cluster.get("certificate-authority-data"),
            cluster.get("certificate-authority"),
            ".crt",
        )
        # kind/minikube kubeconfigs authenticate with client certs, not
        # tokens — support both.
        cert_file = RestKubeClient._materialize(
            user.get("client-certificate-data"),
            user.get("client-certificate"),
            ".crt",
        )
        key_file = RestKubeClient._materialize(
            user.get("client-key-data"), user.get("client-key"), ".key"
        )
        client_cert = (cert_file, key_file) if cert_file and key_file else None
        token = user.get("token")
        return server, token, ca_file, insecure, client_cert

    # ----------------------------------------------------------------- http

    def _request(
        self,
        method: str,
        path: str,
        body: dict | None = None,
        content_type: str = "application/json",
        stream: bool = False,
        timeout: float | None = None,
    ):
        url = self._server + path
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", content_type)
        if self._token:
            req.add_header("Authorization", f"Bearer {self._token}")
        try:
            resp = urllib.request.urlopen(
                req, timeout=timeout or self._timeout, context=self._ssl
            )
        except urllib.error.HTTPError as e:
            msg = e.read().decode(errors="replace")[:500]
            if e.code == 404:
                raise NotFound(msg) from None
            if e.code == 409:
                raise Conflict(msg) from None
            if e.code == 429 and path.endswith("/eviction"):
                raise EvictionBlocked(msg) from None
            raise ApiError(e.code, msg) from None
        except urllib.error.URLError as e:
            raise ApiError(500, f"{method} {path}: {e.reason}") from None
        if stream:
            return resp
        with resp:
            payload = resp.read()
        return json.loads(payload) if payload else {}

    def _path(
        self, kind: str, namespace: str | None, name: str | None = None
    ) -> str:
        """Single-object path; namespace=None addresses the default namespace."""
        prefix, plural, namespaced = _kind_route(kind)
        parts = [prefix]
        if namespaced:
            parts += ["namespaces", urllib.parse.quote(namespace or "default")]
        parts.append(plural)
        if name:
            parts.append(urllib.parse.quote(name))
        return "/".join(parts)

    def _collection_path(self, kind: str, namespace: str | None) -> str:
        """Collection path for list/watch.

        namespace=None means ALL namespaces (the KubeClient/FakeKubeClient
        contract): use the cluster-wide collection, e.g. /api/v1/pods —
        NOT /api/v1/namespaces/default/pods.
        """
        prefix, plural, namespaced = _kind_route(kind)
        if namespaced and namespace is not None:
            return "/".join(
                [prefix, "namespaces", urllib.parse.quote(namespace), plural]
            )
        return "/".join([prefix, plural])

    # ------------------------------------------------------------ interface

    def get(self, kind: str, name: str, namespace: str | None = None) -> dict:
        return self._request("GET", self._path(kind, namespace, name))

    def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: Mapping[str, str] | None = None,
        field_selector: Mapping[str, str] | None = None,
    ) -> list[dict]:
        return self._list(kind, namespace, label_selector, field_selector)[0]

    def _list(
        self,
        kind: str,
        namespace: str | None,
        label_selector: Mapping[str, str] | None = None,
        field_selector: Mapping[str, str] | None = None,
    ) -> tuple[list[dict], str]:
        query = {}
        if label_selector:
            query["labelSelector"] = ",".join(
                f"{k}={v}" for k, v in sorted(label_selector.items())
            )
        if field_selector:
            query["fieldSelector"] = ",".join(
                f"{k}={v}" for k, v in sorted(field_selector.items())
            )
        path = self._collection_path(kind, namespace)
        if query:
            path += "?" + urllib.parse.urlencode(query)
        data = self._request("GET", path)
        items = data.get("items") or []
        for it in items:  # server omits per-item kind in lists
            it.setdefault("kind", kind)
        return items, (data.get("metadata") or {}).get("resourceVersion", "")

    def create(self, kind: str, obj: dict, namespace: str | None = None) -> dict:
        ns = namespace or (obj.get("metadata") or {}).get("namespace")
        return self._request("POST", self._path(kind, ns), body=obj)

    def update(self, kind: str, obj: dict, namespace: str | None = None) -> dict:
        meta = obj.get("metadata") or {}
        ns = namespace or meta.get("namespace")
        return self._request(
            "PUT", self._path(kind, ns, meta.get("name")), body=obj
        )

    def patch(
        self,
        kind: str,
        name: str,
        patch: dict,
        namespace: str | None = None,
    ) -> dict:
        return self._request(
            "PATCH",
            self._path(kind, namespace, name),
            body=patch,
            content_type="application/merge-patch+json",
        )

    def delete(self, kind: str, name: str, namespace: str | None = None) -> None:
        self._request("DELETE", self._path(kind, namespace, name))

    def patch_status(
        self,
        kind: str,
        name: str,
        patch: dict,
        namespace: str | None = None,
    ) -> dict:
        return self._request(
            "PATCH",
            self._path(kind, namespace, name) + "/status",
            body=patch,
            content_type="application/merge-patch+json",
        )

    def bind_pod(self, name: str, namespace: str, node_name: str) -> None:
        """pods/binding subresource — how real schedulers assign nodes."""
        self._request(
            "POST",
            self._path("Pod", namespace, name) + "/binding",
            body={
                "apiVersion": "v1",
                "kind": "Binding",
                "metadata": {"name": name, "namespace": namespace},
                "target": {"apiVersion": "v1", "kind": "Node", "name": node_name},
            },
        )

    def evict_pod(
        self,
        name: str,
        namespace: str,
        grace_period_seconds: int | None = None,
    ) -> None:
        """pods/eviction subresource — graceful, PDB-enforced deletion.
        The server answers 429 when a PodDisruptionBudget has no
        disruptions left; that surfaces as `EvictionBlocked`."""
        body: dict = {
            "apiVersion": "policy/v1",
            "kind": "Eviction",
            "metadata": {"name": name, "namespace": namespace},
        }
        if grace_period_seconds is not None:
            body["deleteOptions"] = {
                "gracePeriodSeconds": grace_period_seconds
            }
        self._request(
            "POST", self._path("Pod", namespace, name) + "/eviction", body=body
        )

    # ---------------------------------------------------------------- watch

    def watch(
        self,
        kind: str,
        namespace: str | None = None,
        stop: Callable[[], bool] | None = None,
    ) -> Iterator[WatchEvent]:
        stop = stop or (lambda: False)
        items, rv = self._list(kind, namespace)
        rv_box = [rv]
        for obj in items:
            yield ("ADDED", obj)
        yield (SYNCED, {})
        backoff = 1.0
        while not stop():
            try:
                yield from self._watch_once(kind, namespace, rv_box, stop)
                backoff = 1.0
            except ApiError as watch_err:
                # 410 Gone (stale resourceVersion) or transient API failure:
                # relist and resume, informer-style. The RESYNC…SYNCED
                # framing lets consumers drop objects deleted during the
                # outage (they won't be re-mentioned in the replay).
                try:
                    items, rv_box[0] = self._list(kind, namespace)
                except ApiError as list_err:
                    # API server still down: back off (capped exponential)
                    # and keep the generator alive rather than dying
                    # mid-outage — but say so, or persistent auth/RBAC
                    # failures would be invisible in the logs.
                    logger.warning(
                        "watch %s: stream failed (%s) and relist failed "
                        "(%s); retrying in %.1fs",
                        kind, watch_err, list_err, backoff,
                    )
                    time.sleep(backoff)
                    backoff = min(backoff * 2, 30.0)
                    continue
                backoff = 1.0
                yield (RESYNC, {})
                for obj in items:
                    yield ("MODIFIED", obj)
                yield (SYNCED, {})

    def _watch_once(
        self,
        kind: str,
        namespace: str | None,
        rv_box: list,
        stop: Callable[[], bool],
    ) -> Iterator[WatchEvent]:
        query = urllib.parse.urlencode(
            {
                "watch": "true",
                "resourceVersion": rv_box[0],
                "timeoutSeconds": "30",
                "allowWatchBookmarks": "true",
            }
        )
        resp = self._request(
            "GET",
            self._collection_path(kind, namespace) + "?" + query,
            stream=True,
            timeout=45.0,
        )
        with resp:
            for line in resp:
                if stop():
                    return
                if not line.strip():
                    continue
                event = json.loads(line)
                etype, obj = event.get("type"), event.get("object") or {}
                rv = (obj.get("metadata") or {}).get("resourceVersion")
                if rv:
                    rv_box[0] = rv
                if etype == "BOOKMARK":
                    continue
                if etype == "ERROR":
                    raise ApiError(410, json.dumps(obj)[:200])
                obj.setdefault("kind", kind)
                yield (etype, obj)

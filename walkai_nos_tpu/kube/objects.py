"""Helpers over dict-shaped Kubernetes objects.

Objects are plain JSON dicts (what the API server speaks); these helpers
keep controller code readable without a types layer.
"""

from __future__ import annotations

import copy
from typing import Any, Mapping


def name(obj: Mapping) -> str:
    return obj.get("metadata", {}).get("name", "")


def namespace(obj: Mapping) -> str:
    return obj.get("metadata", {}).get("namespace", "")


def uid(obj: Mapping) -> str:
    return obj.get("metadata", {}).get("uid", "")


def labels(obj: Mapping) -> dict[str, str]:
    return obj.get("metadata", {}).get("labels") or {}


def annotations(obj: Mapping) -> dict[str, str]:
    return obj.get("metadata", {}).get("annotations") or {}


def owner_references(obj: Mapping) -> list[dict]:
    return obj.get("metadata", {}).get("ownerReferences") or []


def is_owned_by_kind(obj: Mapping, kind: str) -> bool:
    return any(ref.get("kind") == kind for ref in owner_references(obj))


def deep_copy(obj: Mapping) -> dict:
    return copy.deepcopy(dict(obj))


def matches_labels(obj: Mapping, selector: Mapping[str, str]) -> bool:
    lbls = labels(obj)
    return all(lbls.get(k) == v for k, v in selector.items())


def matches_label_selector(lbls: Mapping[str, str], selector: Mapping) -> bool:
    """Full k8s LabelSelector semantics (matchLabels AND matchExpressions
    with In/NotIn/Exists/DoesNotExist) — the `metav1.LabelSelector`
    matching PDBs, pod (anti)affinity terms, and quota selectors use.
    An empty/None selector matches nothing is the PDB convention for
    `null`; here None matches nothing, `{}` matches everything (the
    k8s convention for an empty selector object)."""
    if selector is None:
        return False
    for k, v in (selector.get("matchLabels") or {}).items():
        if lbls.get(k) != v:
            return False
    for expr in selector.get("matchExpressions") or []:
        key = expr.get("key")
        op = expr.get("operator")
        values = expr.get("values") or []
        if op == "In":
            if lbls.get(key) not in values:
                return False
        elif op == "NotIn":
            if key in lbls and lbls[key] in values:
                return False
        elif op == "Exists":
            if key not in lbls:
                return False
        elif op == "DoesNotExist":
            if key in lbls:
                return False
        else:
            return False  # unknown operator: fail closed
    return True


def set_annotations(obj: dict, new: Mapping[str, str | None]) -> dict:
    """Return a copy with annotation updates applied (None deletes)."""
    out = deep_copy(obj)
    ann = dict(annotations(out))
    for k, v in new.items():
        if v is None:
            ann.pop(k, None)
        else:
            ann[k] = v
    out.setdefault("metadata", {})["annotations"] = ann
    return out


def merge_patch(base: Any, patch: Any) -> Any:
    """RFC 7386 JSON Merge Patch: dicts merge recursively, null deletes,
    everything else replaces."""
    if not isinstance(patch, dict):
        return copy.deepcopy(patch)
    if not isinstance(base, dict):
        base = {}
    out = copy.deepcopy(base)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = merge_patch(out.get(k), v)
    return out


def annotation_patch(updates: Mapping[str, str | None]) -> dict:
    """Merge patch touching only metadata.annotations."""
    return {"metadata": {"annotations": dict(updates)}}


# ------------------------------------------------------------------ pod state


def pod_phase(pod: Mapping) -> str:
    return (pod.get("status") or {}).get("phase", "")


def pod_is_pending(pod: Mapping) -> bool:
    """`pkg/util/pod/pod.go:28-31` analogue."""
    return pod_phase(pod) == "Pending"

def pod_is_running(pod: Mapping) -> bool:
    return pod_phase(pod) == "Running"


def pod_is_scheduled(pod: Mapping) -> bool:
    """`pod.go:33-36`: a nodeName is assigned."""
    return bool((pod.get("spec") or {}).get("nodeName"))


def pod_is_unschedulable(pod: Mapping) -> bool:
    """`pod.go:38-55`: PodScheduled condition False/Unschedulable."""
    for cond in (pod.get("status") or {}).get("conditions") or []:
        if (
            cond.get("type") == "PodScheduled"
            and cond.get("status") == "False"
            and cond.get("reason") == "Unschedulable"
        ):
            return True
    return False


def pod_is_owned_by_daemonset(pod: Mapping) -> bool:
    return is_owned_by_kind(pod, "DaemonSet")


def pod_is_owned_by_node(pod: Mapping) -> bool:
    """Static/mirror pods (`pod.go:66-72`)."""
    return is_owned_by_kind(pod, "Node")


def pod_is_preempting(pod: Mapping) -> bool:
    """A nominated node means preemption is in flight (`pod.go:45-47`)."""
    return bool((pod.get("status") or {}).get("nominatedNodeName"))


def pod_priority(pod: Mapping) -> int:
    return int((pod.get("spec") or {}).get("priority") or 0)


def pod_is_more_important(p1: Mapping, p2: Mapping) -> bool:
    """Priority compare (`pod.go:82-88` `IsMoreImportant`)."""
    return pod_priority(p1) > pod_priority(p2)


def extra_resources_could_help_scheduling(pod: Mapping) -> bool:
    """Would creating new slice resources let this pod schedule?
    (`pod.go:28-35`): pending, unschedulable, not already scheduled,
    not preempting, and not node-bound by ownership (DaemonSet/static)."""
    return (
        not pod_is_scheduled(pod)
        and pod_is_pending(pod)
        and pod_is_unschedulable(pod)
        and not pod_is_preempting(pod)
        and not pod_is_owned_by_daemonset(pod)
        and not pod_is_owned_by_node(pod)
    )

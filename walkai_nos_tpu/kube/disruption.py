"""PodDisruptionBudget evaluation for the Eviction API emulation.

The real API server enforces PDBs inside the pods/eviction subresource
handler (a 429 with a DisruptionBudget cause when the budget is spent).
Our fake client and the in-process test API server share this module so
both enforce the same semantics the scheduler's preemption path relies
on; `RestKubeClient` defers to the real server instead.

Reference frame: the restored scheduler spec inherits kube-scheduler's
PDB-aware preemption (`docs/en/docs/elastic-resource-quota/
key-concepts.md:27-75` — scheduling is delegated to the framework, which
evicts through the Eviction API).
"""

from __future__ import annotations

from typing import Mapping

from walkai_nos_tpu.kube import objects


def _parse_maybe_percent(value, total: int) -> int | None:
    """An IntOrString PDB bound: ints pass through, "50%" rounds the way
    the disruption controller does (minAvailable up, handled by caller
    symmetry — we round half away from the budget, i.e. up, which is the
    conservative direction for minAvailable and matches k8s for it).
    A bound the real API server would have rejected at admission
    ("abc%", a float, a negative) returns None; callers fail closed."""
    try:
        if isinstance(value, str) and value.endswith("%"):
            pct = int(value[:-1])
            out = -(-pct * total // 100)  # ceil
        elif isinstance(value, (bool, float)):
            return None  # IntOrString admits neither; int() would mangle
        else:
            out = int(value)
    except (ValueError, TypeError):
        return None
    return out if out >= 0 else None


def _pod_is_healthy(pod: Mapping) -> bool:
    """The disruption controller counts a pod healthy when it is Ready;
    without a kubelet in the loop, bound + Running (or bound + no phase
    yet in fakes) is the closest observable."""
    if (pod.get("status") or {}).get("phase") in ("Succeeded", "Failed"):
        return False
    return bool((pod.get("spec") or {}).get("nodeName"))


def eviction_allowed(
    pod: Mapping, pdbs: list[Mapping], pods: list[Mapping]
) -> tuple[bool, str]:
    """Whether evicting `pod` is allowed by every matching PDB.

    Returns (allowed, reason). `pods` is the pod population to count
    against (same namespace); a PDB whose selector matches the pod
    blocks the eviction when disrupting one more healthy pod would
    drop below minAvailable / exceed maxUnavailable.
    """
    pod_ns = objects.namespace(pod)
    pod_labels = objects.labels(pod)
    for pdb in pdbs:
        if objects.namespace(pdb) != pod_ns:
            continue
        selector = (pdb.get("spec") or {}).get("selector")
        if not objects.matches_label_selector(pod_labels, selector):
            continue
        matching = [
            p
            for p in pods
            if objects.namespace(p) == pod_ns
            and objects.matches_label_selector(objects.labels(p), selector)
        ]
        healthy = sum(1 for p in matching if _pod_is_healthy(p))
        # Evicting an already-unhealthy pod does not reduce the healthy
        # count — the real handler (IfHealthyBudget policy, the default)
        # then only requires the budget to be currently met, so debit
        # the eviction only when the victim is healthy.
        delta = 1 if _pod_is_healthy(pod) else 0
        spec = pdb.get("spec") or {}
        if "minAvailable" in spec:
            min_available = _parse_maybe_percent(
                spec["minAvailable"], len(matching)
            )
            if min_available is None:
                return False, (
                    f"pdb {objects.name(pdb)}: malformed minAvailable "
                    f"{spec['minAvailable']!r}, failing closed"
                )
            if healthy - delta < min_available:
                return False, (
                    f"pdb {objects.name(pdb)}: eviction would leave "
                    f"{healthy - delta} healthy < minAvailable "
                    f"{min_available}"
                )
        if "maxUnavailable" in spec:
            max_unavailable = _parse_maybe_percent(
                spec["maxUnavailable"], len(matching)
            )
            if max_unavailable is None:
                return False, (
                    f"pdb {objects.name(pdb)}: malformed maxUnavailable "
                    f"{spec['maxUnavailable']!r}, failing closed"
                )
            unavailable = len(matching) - healthy
            if unavailable + delta > max_unavailable:
                return False, (
                    f"pdb {objects.name(pdb)}: eviction would make "
                    f"{unavailable + delta} unavailable > maxUnavailable "
                    f"{max_unavailable}"
                )
    return True, ""

"""Lease-based leader election for the cluster-scope partitioner.

The reference enables controller-runtime leader election for the
gpupartitioner (`config/gpupartitioner/manager/gpu_partitioner_config.yaml:9-21`)
while agents run with `leaderElect: false`. Same semantics here on
`coordination.k8s.io/v1` Leases: acquire when unheld/expired, renew at
`renew_interval`, step down (callback) if renewal falls behind
`lease_duration`.
"""

from __future__ import annotations

import logging
import math
import threading
import time
import uuid
from datetime import datetime, timedelta, timezone
from typing import Callable

from walkai_nos_tpu.kube.client import ApiError, Conflict, KubeClient, NotFound

logger = logging.getLogger(__name__)


def _now() -> datetime:
    return datetime.now(timezone.utc)


def _fmt(t: datetime) -> str:
    return t.strftime("%Y-%m-%dT%H:%M:%S.%f") + "Z"


def _parse(s: str) -> datetime:
    """Any RFC3339 form — fractional seconds optional, 'Z' or offset
    (other clients may serialize either; treating a valid form as
    unparseable would let a candidate steal a still-valid lease)."""
    t = datetime.fromisoformat(s.replace("Z", "+00:00"))
    if t.tzinfo is None:
        t = t.replace(tzinfo=timezone.utc)
    return t.astimezone(timezone.utc)


class LeaderElector:
    def __init__(
        self,
        kube: KubeClient,
        lease_name: str,
        namespace: str = "walkai-nos",
        identity: str | None = None,
        lease_duration: float = 15.0,
        renew_interval: float = 5.0,
        on_started_leading: Callable[[], None] | None = None,
        on_stopped_leading: Callable[[], None] | None = None,
    ) -> None:
        self._kube = kube
        self._name = lease_name
        self._ns = namespace
        self.identity = identity or f"{lease_name}-{uuid.uuid4().hex[:8]}"
        self._duration = lease_duration
        self._renew = renew_interval
        self._on_start = on_started_leading or (lambda: None)
        self._on_stop = on_stopped_leading or (lambda: None)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.is_leader = threading.Event()

    # ------------------------------------------------------------- lease ops

    def _try_acquire_or_renew(self) -> bool:
        now = _now()
        body = {
            "metadata": {"name": self._name, "namespace": self._ns},
            "spec": {
                "holderIdentity": self.identity,
                # k8s requires an integer; round up so sub-second test
                # durations don't truncate to an instantly-expired lease.
                "leaseDurationSeconds": max(1, math.ceil(self._duration)),
                "acquireTime": _fmt(now),
                "renewTime": _fmt(now),
            },
        }
        try:
            lease = self._kube.get("Lease", self._name, self._ns)
        except NotFound:
            try:
                self._kube.create("Lease", body, self._ns)
                return True
            except (Conflict, ApiError):
                return False
        spec = lease.get("spec") or {}
        holder = spec.get("holderIdentity")
        renew_s = spec.get("renewTime")
        expired = True
        if renew_s:
            try:
                expires = _parse(renew_s) + timedelta(
                    seconds=float(spec.get("leaseDurationSeconds", self._duration))
                )
                expired = now > expires
            except ValueError:
                expired = True
        if holder not in (None, "", self.identity) and not expired:
            return False
        if holder == self.identity:
            body["spec"]["acquireTime"] = spec.get(
                "acquireTime", body["spec"]["acquireTime"]
            )
        # Conditional update on the read resourceVersion so two candidates
        # racing on an expired lease can't both win (client-go guards the
        # same way; a merge patch cannot conflict).
        lease["spec"] = body["spec"]
        try:
            self._kube.update("Lease", lease, self._ns)
            return True
        except ApiError:  # Conflict: someone else won the race
            return False

    # -------------------------------------------------------------- lifecycle

    def _run(self) -> None:
        leading = False
        last_renew = 0.0
        while not self._stop.is_set():
            ok = False
            try:
                ok = self._try_acquire_or_renew()
            except ApiError as e:
                logger.warning("leader election: API error: %s", e)
            now = time.monotonic()
            if ok:
                last_renew = now
                if not leading:
                    leading = True
                    self.is_leader.set()
                    logger.info(
                        "leader election: %s acquired %s", self.identity, self._name
                    )
                    self._on_start()
            elif leading and now - last_renew > self._duration:
                leading = False
                self.is_leader.clear()
                logger.warning(
                    "leader election: %s lost %s", self.identity, self._name
                )
                self._on_stop()
            self._stop.wait(self._renew)
        if leading:
            self.is_leader.clear()
            self._on_stop()

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"leader-{self._name}"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)

    def wait_for_leadership(self, timeout: float | None = None) -> bool:
        return self.is_leader.wait(timeout)

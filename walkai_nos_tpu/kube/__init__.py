"""Minimal Kubernetes machinery: typed-dict objects, client interface,
in-memory fake API server (the envtest analogue), REST client, and a small
controller runtime (watch -> predicates -> workqueue -> reconcile).

The reference builds on controller-runtime; this package provides the same
architectural seams (client interface injected everywhere, predicates to cut
watch chatter, per-controller work queues with bounded concurrency,
requeue-after) without external dependencies.
"""

from walkai_nos_tpu.kube import objects  # noqa: F401
from walkai_nos_tpu.kube.client import (  # noqa: F401
    ApiError,
    Conflict,
    KubeClient,
    NotFound,
)
from walkai_nos_tpu.kube.fake import FakeKubeClient  # noqa: F401
from walkai_nos_tpu.kube.runtime import (  # noqa: F401
    Controller,
    Manager,
    Request,
    Result,
)

"""KubeClient: the API-server boundary interface.

Everything above this line (controllers, planners, exporters) is written
against this interface, mirroring how the reference injects
controller-runtime's `client.Client` everywhere so envtest/mocks can stand
in (SURVEY.md §4 "test seams"). Implementations: `FakeKubeClient`
(in-memory, tests/simulation) and `RestKubeClient` (real API server).
"""

from __future__ import annotations

import abc
from typing import Callable, Iterator, Mapping

# A watch event: ("ADDED" | "MODIFIED" | "DELETED" | sync marker, object-dict)
WatchEvent = tuple[str, dict]

# Sync markers framing full-snapshot replays in a watch stream. The initial
# ADDED burst ends with (SYNCED, {}); after an outage, an informer-style
# relist is framed as (RESYNC, {}), MODIFIED per survivor, (SYNCED, {}).
# Between a RESYNC and its SYNCED the stream has named every live object,
# so consumers tracking object sets can drop anything not re-mentioned —
# that's how deletions missed during an outage are reconciled (the analogue
# of client-go's DeletedFinalStateUnknown handling, resolved consumer-side
# where the last-seen content lives).
RESYNC = "RESYNC"
SYNCED = "SYNCED"


class ApiError(Exception):
    def __init__(self, status: int, message: str = ""):
        super().__init__(f"{status}: {message}")
        self.status = status
        self.message = message


class NotFound(ApiError):
    def __init__(self, message: str = "not found"):
        super().__init__(404, message)


class Conflict(ApiError):
    def __init__(self, message: str = "conflict"):
        super().__init__(409, message)


class EvictionBlocked(ApiError):
    """The Eviction API refused: a PodDisruptionBudget has no
    disruptions left (HTTP 429 with a DisruptionBudget cause)."""

    def __init__(self, message: str = "disruption budget exhausted"):
        super().__init__(429, message)


class KubeClient(abc.ABC):
    """CRUD + watch over dict-shaped objects.

    `kind` is a plural-insensitive kind name ("Node", "Pod", "Lease",
    "ElasticQuota", ...). Namespaced kinds take `namespace`; cluster-scoped
    kinds ignore it.
    """

    @abc.abstractmethod
    def get(self, kind: str, name: str, namespace: str | None = None) -> dict: ...

    @abc.abstractmethod
    def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: Mapping[str, str] | None = None,
        field_selector: Mapping[str, str] | None = None,
    ) -> list[dict]: ...

    @abc.abstractmethod
    def create(self, kind: str, obj: dict, namespace: str | None = None) -> dict: ...

    @abc.abstractmethod
    def update(self, kind: str, obj: dict, namespace: str | None = None) -> dict: ...

    @abc.abstractmethod
    def patch(
        self,
        kind: str,
        name: str,
        patch: dict,
        namespace: str | None = None,
    ) -> dict:
        """JSON merge patch (RFC 7386) — the reference's `client.MergeFrom`
        optimistic-concurrency pattern (`partitioner.go:65`)."""
        ...

    @abc.abstractmethod
    def delete(self, kind: str, name: str, namespace: str | None = None) -> None: ...

    def patch_status(
        self,
        kind: str,
        name: str,
        patch: dict,
        namespace: str | None = None,
    ) -> dict:
        """Patch an object's status. Real API servers route this through
        the /status subresource when the CRD enables it (overridden in
        RestKubeClient) — a main-resource write would silently drop status
        changes there. Fakes store status inline, so default to patch."""
        return self.patch(kind, name, patch, namespace)

    def bind_pod(self, name: str, namespace: str, node_name: str) -> None:
        """Assign a pod to a node. Real API servers use the pods/binding
        subresource (overridden in RestKubeClient); the default mutates
        spec.nodeName directly, which is what fakes accept."""
        self.patch("Pod", name, {"spec": {"nodeName": node_name}}, namespace)

    def evict_pod(
        self,
        name: str,
        namespace: str,
        grace_period_seconds: int | None = None,
    ) -> None:
        """Graceful, PDB-respecting deletion through the pods/eviction
        subresource. Raises `EvictionBlocked` when a PodDisruptionBudget
        has no disruptions left (real servers enforce this server-side;
        `FakeKubeClient` emulates it via `kube.disruption`). The default
        falls back to a plain delete for implementations without the
        subresource."""
        self.delete("Pod", name, namespace)

    @abc.abstractmethod
    def watch(
        self,
        kind: str,
        namespace: str | None = None,
        stop: Callable[[], bool] | None = None,
    ) -> Iterator[WatchEvent]:
        """Stream events. Implementations yield an initial synthetic ADDED
        for each existing object followed by a (SYNCED, {}) marker, then
        live events, and poll `stop` to terminate. Recoverable stream
        outages are resolved with a RESYNC…SYNCED framed relist replay
        (see the marker docs above)."""
        ...

"""Controller runtime: watch -> predicates -> workqueue -> reconcile.

The dependency-free equivalent of controller-runtime's manager/controller
machinery the reference builds every binary on: each controller watches one
kind, filters events through predicates, deduplicates work on a keyed queue,
and runs `reconcile(request)` on a bounded worker pool with
requeue/requeue-after semantics and per-key exponential backoff on error
(mirrors `MaxConcurrentReconciles`, `Result{RequeueAfter}`, and the default
rate limiter).
"""

from __future__ import annotations

import heapq
import logging
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Callable, Mapping

from walkai_nos_tpu.kube import objects
from walkai_nos_tpu.kube.client import RESYNC, SYNCED, KubeClient
from walkai_nos_tpu.kube.predicates import Predicate

logger = logging.getLogger(__name__)

# Process-global controller metrics, served by the binaries' /metrics
# endpoint — the analogue of controller-runtime's built-in Prometheus
# registry (reconcile totals/errors/duration; SURVEY.md §5.5).
_metrics = None


def set_metrics_registry(metrics) -> None:
    global _metrics
    _metrics = metrics


def _record_reconcile(controller: str, outcome: str, seconds: float) -> None:
    if _metrics is None:
        return
    labels = {"controller": controller, "result": outcome}
    _metrics.counter_add(
        "nos_reconcile_total", 1, labels,
        help_text="Reconciliations per controller and outcome",
    )
    _metrics.counter_add(
        "nos_reconcile_seconds_sum", seconds, {"controller": controller},
        help_text="Cumulative reconcile wall time",
    )


@dataclass(frozen=True)
class Request:
    """A reconcile request: the object's key."""

    name: str
    namespace: str = ""


@dataclass
class Result:
    """Reconcile outcome (`reconcile.Result` analogue)."""

    requeue: bool = False
    requeue_after: float | None = None


Reconciler = Callable[[Request], Result]

_BACKOFF_BASE = 0.05
_BACKOFF_MAX = 30.0


class _WorkQueue:
    """Keyed, deduplicating, delay-capable work queue."""

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._pending: set[Request] = set()
        self._active: set[Request] = set()
        self._redo: set[Request] = set()
        self._delayed: list[tuple[float, int, Request]] = []
        self._seq = 0
        self._shutdown = False

    def add(self, req: Request) -> None:
        with self._cond:
            if self._shutdown:
                return
            if req in self._active:
                self._redo.add(req)
            else:
                self._pending.add(req)
            self._cond.notify()

    def add_after(self, req: Request, delay: float) -> None:
        with self._cond:
            if self._shutdown:
                return
            self._seq += 1
            heapq.heappush(self._delayed, (time.monotonic() + delay, self._seq, req))
            self._cond.notify()

    def get(self, timeout: float = 0.2) -> Request | None:
        with self._cond:
            deadline = time.monotonic() + timeout
            while True:
                now = time.monotonic()
                while self._delayed and self._delayed[0][0] <= now:
                    _, _, req = heapq.heappop(self._delayed)
                    if req in self._active:
                        self._redo.add(req)
                    else:
                        self._pending.add(req)
                if self._shutdown:
                    return None
                ready = self._pending - self._active
                if ready:
                    req = sorted(ready, key=lambda r: (r.namespace, r.name))[0]
                    self._pending.discard(req)
                    self._active.add(req)
                    return req
                wait = deadline - now
                if self._delayed:
                    wait = min(wait, self._delayed[0][0] - now)
                if wait <= 0:
                    return None
                self._cond.wait(wait)

    def done(self, req: Request) -> None:
        with self._cond:
            self._active.discard(req)
            if req in self._redo:
                self._redo.discard(req)
                self._pending.add(req)
                self._cond.notify()

    def shutdown(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    @property
    def is_shutdown(self) -> bool:
        with self._cond:
            return self._shutdown


class Controller:
    """One watch + one reconciler (`ctrl.NewControllerManagedBy` analogue)."""

    def __init__(
        self,
        name: str,
        client: KubeClient,
        kind: str,
        reconciler: Reconciler,
        predicates: list[Predicate] | None = None,
        max_concurrent: int = 1,
        namespace: str | None = None,
    ) -> None:
        self.name = name
        self.client = client
        self.kind = kind
        self.reconciler = reconciler
        self.predicates = predicates or []
        self.max_concurrent = max_concurrent
        self.namespace = namespace
        self.queue = _WorkQueue()
        self._cache: dict[tuple[str, str], dict] = {}
        self._cache_lock = threading.Lock()
        self._failures: dict[Request, int] = {}
        self._threads: list[threading.Thread] = []
        self._stop = False
        self.watch_ready = threading.Event()

    # ----------------------------------------------------------------- watch

    def _watch_loop(self) -> None:
        backoff = 0.5
        while not self._stop:
            try:
                stream = self.client.watch(
                    self.kind, self.namespace, stop=lambda: self._stop
                )
                # The client registers the watch at call time (see
                # FakeKubeClient.watch); signal readiness so start() can
                # guarantee no event published after start() is missed.
                self.watch_ready.set()
                # Keys cached from a previous stream but not (yet)
                # re-mentioned by this one. Whatever survives to the SYNCED
                # marker was deleted while no stream was up — prune it with
                # a synthetic DELETED (carrying the last-seen content so
                # predicates still match). The snapshot comes from the
                # stream's own initial list, so there is no list-vs-watch
                # race window.
                with self._cache_lock:
                    unconfirmed: set | None = set(self._cache)
                for event, obj in stream:
                    backoff = 0.5  # stream delivering: reset failure backoff
                    if event == RESYNC:
                        with self._cache_lock:
                            unconfirmed = set(self._cache)
                    elif event == SYNCED:
                        if unconfirmed:
                            with self._cache_lock:
                                stale = [
                                    self._cache[k]
                                    for k in unconfirmed
                                    if k in self._cache
                                ]
                            for dead in stale:
                                self._handle_event("DELETED", dead)
                        unconfirmed = None
                    else:
                        if unconfirmed is not None:
                            unconfirmed.discard(
                                (objects.namespace(obj), objects.name(obj))
                            )
                        self._handle_event(event, obj)
                    if self._stop:
                        break
            except Exception:
                if not self._stop:
                    # Capped exponential backoff: a persistently failing
                    # watch (e.g. a CRD that is simply not installed)
                    # must not hot-loop full-traceback warnings forever.
                    logger.warning(
                        "%s: watch failed, retrying in %.1fs:\n%s",
                        self.name,
                        backoff,
                        traceback.format_exc(),
                    )
                    time.sleep(backoff)
                    backoff = min(backoff * 2, 30.0)

    def _handle_event(self, event: str, obj: Mapping) -> None:
        key = (objects.namespace(obj), objects.name(obj))
        with self._cache_lock:
            old = self._cache.get(key)
            if event == "DELETED":
                self._cache.pop(key, None)
            else:
                self._cache[key] = objects.deep_copy(obj)
        for pred in self.predicates:
            if not pred(event, obj, old):
                return
        self.queue.add(Request(name=key[1], namespace=key[0]))

    # --------------------------------------------------------------- workers

    def _worker_loop(self) -> None:
        while not self._stop:
            req = self.queue.get()
            if req is None:
                continue
            started = time.monotonic()
            try:
                result = self.reconciler(req)
                self._failures.pop(req, None)
                _record_reconcile(
                    self.name, "success", time.monotonic() - started
                )
                if result and result.requeue_after is not None:
                    self.queue.add_after(req, result.requeue_after)
                elif result and result.requeue:
                    self.queue.add(req)
            except Exception:
                _record_reconcile(
                    self.name, "error", time.monotonic() - started
                )
                n = self._failures.get(req, 0) + 1
                self._failures[req] = n
                delay = min(_BACKOFF_BASE * (2 ** (n - 1)), _BACKOFF_MAX)
                logger.warning(
                    "%s: reconcile %s failed (attempt %d, retry in %.2fs):\n%s",
                    self.name,
                    req,
                    n,
                    delay,
                    traceback.format_exc(),
                )
                self.queue.add_after(req, delay)
            finally:
                self.queue.done(req)

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._stop = False
        if self.queue.is_shutdown:
            # A stopped controller can be restarted (leader election loses
            # and re-acquires the lease); a shut-down queue is dead, so
            # build a fresh one.
            self.queue = _WorkQueue()
        self.watch_ready.clear()
        t = threading.Thread(
            target=self._watch_loop, name=f"{self.name}-watch", daemon=True
        )
        t.start()
        self._threads.append(t)
        if not self.watch_ready.wait(timeout=5.0):
            logger.warning("%s: watch not established within 5s", self.name)
        for i in range(self.max_concurrent):
            w = threading.Thread(
                target=self._worker_loop, name=f"{self.name}-worker-{i}", daemon=True
            )
            w.start()
            self._threads.append(w)

    def stop(self) -> None:
        self._stop = True
        self.queue.shutdown()
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads.clear()


@dataclass
class Manager:
    """Runs a set of controllers (`ctrl.Manager` analogue)."""

    controllers: list[Controller] = field(default_factory=list)
    # Resources closed on stop (e.g. a SharedWatchClient's pump threads
    # + upstream streams must not outlive the manager).
    _owned: list = field(default_factory=list)

    def add(self, controller: Controller) -> None:
        self.controllers.append(controller)

    def own(self, closeable) -> None:
        """Register a resource whose close() is tied to this manager."""
        self._owned.append(closeable)

    def start(self) -> None:
        for c in self.controllers:
            c.start()

    def stop(self) -> None:
        for c in self.controllers:
            c.stop()
        for resource in self._owned:
            try:
                resource.close()
            except Exception:
                logger.exception("closing managed resource failed")

    def __enter__(self) -> "Manager":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

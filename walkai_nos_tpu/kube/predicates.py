"""Watch-event predicates — cut reconcile chatter at the source.

Analogue of `pkg/util/predicate/predicates.go:27-76`. A predicate sees the
watch event type, the new object, and (for MODIFIED) the previous object
snapshot held by the controller's cache.
"""

from __future__ import annotations

from typing import Callable, Mapping

from walkai_nos_tpu.kube import objects

# (event_type, new_obj, old_obj|None) -> bool
Predicate = Callable[[str, Mapping, Mapping | None], bool]


def matching_name(name: str, namespace: str | None = None) -> Predicate:
    """Only events for one specific object (`predicates.go:27-45`) — the
    node agents watch only their own Node."""

    def pred(_event: str, obj: Mapping, _old: Mapping | None) -> bool:
        if objects.name(obj) != name:
            return False
        return namespace is None or objects.namespace(obj) == namespace

    return pred


def exclude_delete() -> Predicate:
    """Drop DELETED events (`predicates.go:70-76`)."""
    return lambda event, _obj, _old: event != "DELETED"


def annotations_changed() -> Predicate:
    """MODIFIED events only when annotations differ (`predicates.go:61-68`);
    ADDED always passes."""

    def pred(event: str, obj: Mapping, old: Mapping | None) -> bool:
        if event != "MODIFIED" or old is None:
            return True
        return objects.annotations(obj) != objects.annotations(old)

    return pred


def status_annotations_changed() -> Predicate:
    """MODIFIED events only when the AGENT-written annotations (status
    slices/shares + plan ack) differ — the partitioner's pending-pod
    mapper keys on these so its own spec/plan writes can't re-trigger it
    (a spec write would otherwise re-enqueue the pod whose planning just
    wrote that spec, looping plan-id churn through the API server).
    ADDED always passes."""
    from walkai_nos_tpu.api import constants

    def status_view(obj: Mapping) -> dict:
        return {
            k: v
            for k, v in objects.annotations(obj).items()
            if k.startswith(constants.ANNOTATION_TPU_STATUS_PREFIX)
            or k == constants.ANNOTATION_REPORTED_PARTITIONING_PLAN
        }

    def pred(event: str, obj: Mapping, old: Mapping | None) -> bool:
        if event != "MODIFIED" or old is None:
            return True
        return status_view(obj) != status_view(old)

    return pred


def node_resources_changed() -> Predicate:
    """Fires on MODIFIED only when status.capacity changed while
    status.allocatable did not — the kubelet is re-advertising resources
    (`predicates.go:47-59` `NodeResourcesChanged`)."""

    def pred(event: str, obj: Mapping, old: Mapping | None) -> bool:
        if event != "MODIFIED" or old is None:
            return True
        new_cap = (obj.get("status") or {}).get("capacity") or {}
        old_cap = (old.get("status") or {}).get("capacity") or {}
        new_alloc = (obj.get("status") or {}).get("allocatable") or {}
        old_alloc = (old.get("status") or {}).get("allocatable") or {}
        return new_cap != old_cap and new_alloc == old_alloc

    return pred


def has_label(key: str, value: str | None = None) -> Predicate:
    """Only objects carrying a label (optionally with a specific value)."""

    def pred(_event: str, obj: Mapping, _old: Mapping | None) -> bool:
        lbls = objects.labels(obj)
        if key not in lbls:
            return False
        return value is None or lbls[key] == value

    return pred


def any_of(*preds: Predicate) -> Predicate:
    return lambda e, o, old: any(p(e, o, old) for p in preds)


def all_of(*preds: Predicate) -> Predicate:
    return lambda e, o, old: all(p(e, o, old) for p in preds)

"""Cluster-scope partitioner controllers.

Analogue of `internal/controllers/gpupartitioner/`: the pod controller
reacts to pending pods requesting TPU slices by re-tiling a node; the node
controller initializes freshly labeled TPU nodes.
"""

from walkai_nos_tpu.controllers.partitioner.pod_controller import (  # noqa: F401
    PodController,
    make_node_event_mapper,
)
from walkai_nos_tpu.controllers.partitioner.node_controller import (  # noqa: F401
    NodeController,
)

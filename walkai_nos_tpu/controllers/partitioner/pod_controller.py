"""Pod controller: pending pod -> repartitioned node (the core loop).

Port of `internal/controllers/gpupartitioner/mig_controller.go:35-213`:
for a pending+unschedulable pod requesting `walkai.io/tpu-<shape>` slices,
list tiling-partitioned nodes; if no node already exposes the wanted
profiles free, walk nodes first-fit and try a geometry transition; on
success write the new spec annotations + plan ID. Single-threaded
(MaxConcurrentReconciles=1, `mig_controller.go:204`) so concurrent pending
pods can't race partitioning decisions.

Retry is event-driven, like the reference's watch mapping
(`mig_controller.go:180-207`): a decision is a pure function of pod + node
state, so a failed attempt is only worth repeating when a partitioned
node actually changed — `make_node_event_mapper` re-enqueues every pending
slice pod on node add/annotation-change events instead of polling.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Callable

from walkai_nos_tpu.api import constants
from walkai_nos_tpu.kube import objects
from walkai_nos_tpu.kube.client import KubeClient, NotFound
from walkai_nos_tpu.kube.runtime import Request, Result
from walkai_nos_tpu.partitioning.partitioner import Partitioner
from walkai_nos_tpu.partitioning.plan_id import new_partitioning_plan_id
from walkai_nos_tpu.partitioning.state import build_node_partitioning
from walkai_nos_tpu.tpu.partitioning import Geometry, PartitioningKind
from walkai_nos_tpu.tpu.sharing.node import SharingNode
from walkai_nos_tpu.tpu.sharing.profile import get_requested_shared_profiles
from walkai_nos_tpu.tpu.tiling.node import Node
from walkai_nos_tpu.tpu.tiling.profile import get_requested_profiles
from walkai_nos_tpu.utils.batcher import Batcher

logger = logging.getLogger(__name__)


def make_node_event_mapper(
    kube: KubeClient, enqueue: Callable[[Request], None]
) -> Callable[[Request], Result]:
    """Node events -> pending-slice-pod reconciles.

    The analogue of the reference's `Watches(&corev1.Node{},
    handler.EnqueueRequestsFromMapFunc(...))` wiring
    (`mig_controller.go:180-207`): whenever a partitioned node is added or
    its annotations change (capacity freed, a retile reported, a plan
    acked), every pod that re-tiling could still help is re-enqueued on
    the pod controller's queue. This replaces periodic pending-pod polling
    — with no node change, a retry would recompute the same answer."""

    def reconcile(_request: Request) -> Result:
        for pod in kube.list("Pod"):
            if not objects.extra_resources_could_help_scheduling(pod):
                continue
            if not get_requested_profiles(pod) and not (
                get_requested_shared_profiles(pod)
            ):
                continue
            enqueue(
                Request(
                    name=objects.name(pod), namespace=objects.namespace(pod)
                )
            )
        # Always wake the planner once per node event, pods or not: the
        # pool-consistency sweep (`reconcile_batch`'s janitor) must see
        # a share REPORT that lands after the plan pass that stranded
        # it — with only pending-pod wakeups, a strand surfacing when
        # nothing is pending would be advertised forever.
        enqueue(Request(name="", namespace=""))
        return Result()

    return reconcile


class PodController:
    def __init__(
        self,
        kube: KubeClient,
        partitioner: Partitioner | None = None,
        plan_id_fn: Callable[[], str] = new_partitioning_plan_id,
    ) -> None:
        self._kube = kube
        self._partitioner = partitioner or Partitioner(kube)
        # Injectable plan-ID generator (test seam, `mig_controller.go:209-213`).
        self._plan_id_fn = plan_id_fn

    # ------------------------------------------------------------- reconcile

    def reconcile(self, request: Request) -> Result:
        """Single-pod mode: a one-element batch. Same decisions as the
        batch-window path — no write when a node already provides the
        wanted profiles free (the scheduler will bind the pod on its
        next cycle, `mig_controller.go:121-144`), first-fit geometry
        transition otherwise — with one planning implementation."""
        self.reconcile_batch([request])
        return Result()

    # ------------------------------------------------------------ batch mode

    def reconcile_batch(self, requests: list[Request]) -> None:
        """Plan a whole batch of pending pods in one pass (the upstream
        batch-window behavior, `gpu_partitioner_config.yaml:23-33`, which
        the reference fork orphaned along with its Batcher).

        One node snapshot serves the entire batch, with simulated
        placement (`Node.add_pod`) claiming free slices as pods are
        satisfied — so two pods wanting the same free slice cannot both
        be skipped as "already available" — and each node's spec is
        written at most once per batch, however many pods land on it
        (one plan cycle for the agents instead of one per pod)."""
        pods: list[dict] = []
        seen: set[tuple[str, str]] = set()
        for req in requests:
            if not req.name:
                continue  # planner wake-up sentinel (node event mapper)
            key = (req.namespace, req.name)
            if key in seen:
                continue
            seen.add(key)
            try:
                pod = self._kube.get(
                    "Pod", req.name, req.namespace or None
                )
            except NotFound:
                continue
            if self._should_consider_pod(pod):
                pods.append(pod)
        # Deterministic order: oldest pending pod plans first (RFC3339
        # creation timestamps sort lexicographically).
        pods.sort(
            key=lambda p: (
                p.get("metadata", {}).get("creationTimestamp", ""),
                objects.namespace(p),
                objects.name(p),
            )
        )
        if pods:
            self._plan_pass(
                pods, get_requested_profiles, self._list_tiling_nodes,
                Node.from_node, "repartitioned", include_pools=True,
            )
            self._plan_pass(
                pods, get_requested_shared_profiles,
                self._list_sharing_nodes, SharingNode.from_node,
                "re-shared",
            )
        # Pool-consistency janitor, pending pods or not: a plan pass
        # whose snapshot predated a mate's share report leaves that
        # share stranded AFTER the pass — only an event-driven sweep
        # can retire it (`pool.stranded_share_retiles`, which refuses
        # to touch pools mid-initialization or mid-plan).
        self._sweep_stranded_pool_shares()

    def _plan_pass(
        self, pods: list[dict], wanted_fn, list_nodes, node_factory,
        verb: str, include_pools: bool = False,
    ) -> None:
        from walkai_nos_tpu.tpu.tiling.pool import (
            PoolNode,
            group_pool_members,
        )

        wanted_pods = [
            (pod, wanted) for pod in pods if (wanted := wanted_fn(pod))
        ]
        if not wanted_pods:
            return
        # Mutable views: [writes_fn, simulated view, changed?]. Claimed
        # slices stay `used` in the simulation, which also protects them
        # from eviction by later pods' geometry transitions (the mesh
        # search never evicts used slices). `writes_fn(view)` yields the
        # (node object, NodePartitioning) writes realizing the view — one
        # for a single-host node, one per member host for a pool.
        node_objs = list_nodes()
        pools: dict[str, list[dict]] = {}
        if include_pools:
            node_objs, pools = group_pool_members(node_objs)
        views: list[list] = [
            [
                lambda v, obj=node_obj: [(obj, build_node_partitioning(v))],
                node_factory(
                    objects.name(node_obj),
                    objects.labels(node_obj),
                    objects.annotations(node_obj),
                ),
                False,
            ]
            for node_obj in node_objs
        ]
        for pool_name in sorted(pools):
            pool = PoolNode.from_nodes(pool_name, pools[pool_name])
            if pool is None:
                continue  # not coordinatable (yet): refusal path
            views.append([lambda v: v.build_partitionings(), pool, False])
        for pod, wanted in wanted_pods:
            if self._place_in_views(views, wanted):
                continue
            logger.info(
                "pod controller: no node can provide %s for pod %s/%s",
                wanted, objects.namespace(pod), objects.name(pod),
            )
        for writes_fn, view, changed in views:
            if not changed:
                continue
            plan_id = self._plan_id_fn()
            for node_obj, partitioning in writes_fn(view):
                self._partitioner.apply_partitioning(
                    node_obj, partitioning, plan_id
                )
            logger.info(
                "pod controller: %s %s for a batch of %d pending "
                "pods (plan %s)",
                verb, view.name, len(wanted_pods), plan_id,
            )

    @staticmethod
    def _place_in_views(views: list[list], wanted: Geometry) -> bool:
        """The first-fit planning loop (`mig_controller.go:121-207`),
        shared by tiling and sharing — both node models expose the same
        search surface (has_free_capacity / clone / update_geometry_for /
        provides_profiles / add_pod)."""
        # Already available on the (claimed) view: consume it so the
        # next pod in the batch sees the truth.
        for entry in views:
            if entry[1].provides_profiles(wanted):
                entry[1].add_pod(wanted)
                return True
        # First-fit geometry transition (`mig_controller.go:146-207`).
        for entry in views:
            if not entry[1].has_free_capacity():
                continue
            candidate = entry[1].clone()
            if not candidate.update_geometry_for(wanted):
                continue
            if not candidate.provides_profiles(wanted):
                continue
            candidate.add_pod(wanted)
            entry[1] = candidate
            entry[2] = True
            return True
        return False

    # --------------------------------------------------------------- helpers

    def _sweep_stranded_pool_shares(self) -> None:
        """Re-tile reported free pool shares no complete block can back
        (see `pool.stranded_share_retiles` for the race this closes).

        Lists nodes FRESH rather than reusing a plan pass's snapshot:
        the pass may just have written specs, and the janitor's
        mid-plan guard reads them. Cost when nothing is wrong: one
        node list + a label check per node (annotation parsing happens
        only for pool members) — per planner wake-up, not per pod."""
        from walkai_nos_tpu.tpu.tiling.pool import (
            group_pool_members,
            stranded_share_retiles,
        )

        _singles, pools = group_pool_members(self._list_tiling_nodes())
        for pool_name in sorted(pools):
            writes = stranded_share_retiles(pool_name, pools[pool_name])
            if not writes:
                continue
            plan_id = self._plan_id_fn()
            for node_obj, partitioning in writes:
                self._partitioner.apply_partitioning(
                    node_obj, partitioning, plan_id
                )
            logger.info(
                "pod controller: re-tiled %d stranded pool share(s) "
                "in %s (plan %s)",
                len(writes), pool_name, plan_id,
            )

    def _should_consider_pod(self, pod: dict) -> bool:
        """Re-tiling only helps pods that new slice resources could
        schedule (`mig_controller.go:100-111` ->
        `ExtraResourcesCouldHelpScheduling`, `pkg/util/pod/pod.go:28-35`):
        pending + unschedulable, not mid-preemption, and not node-bound by
        ownership (DaemonSet/static pods follow their node, not resources)."""
        return objects.extra_resources_could_help_scheduling(pod)

    def _list_tiling_nodes(self) -> list[dict]:
        return self._kube.list(
            "Node",
            label_selector={
                constants.LABEL_TPU_PARTITIONING: PartitioningKind.TILING.value
            },
        )

    def _list_sharing_nodes(self) -> list[dict]:
        return self._kube.list(
            "Node",
            label_selector={
                constants.LABEL_TPU_PARTITIONING: PartitioningKind.SHARING.value
            },
        )


class BatchingPodReconciler:
    """Batching front of the pod controller, in one of two modes.

    **Drain mode (idle == 0, the default).** The worker takes every
    request queued the moment it is free and plans immediately: a batch
    is whatever arrived during the previous plan pass. Coalescing is
    proportional to actual planning cost (~1 ms/pod measured), so a pod
    never waits for a burst's tail — under a steady arrival stream the
    classic idle window made every pod pay the whole burst duration
    plus the idle wait before planning even started (the round-3 p50
    time-to-scheduled regression).

    **Window mode (idle > 0).** The upstream batch-window semantics the
    reference fork orphaned (`pkg/util/batcher.go:25-130` + the knobs,
    `gpu_partitioner_config.yaml:23-33`): the first request opens the
    timeout window, each request restarts the idle window, the batch is
    planned when either closes. Maximizes pods-per-plan — fewest
    re-tile writes per node — where agent actuation cycles are scarcer
    than latency.

    The Controller's per-key retry/backoff does not apply here —
    `reconcile` returns before planning runs. That is safe for this
    loop: a planning decision is a pure function of pod + node state,
    and the node-event mapper re-enqueues every still-pending slice pod
    whenever a partitioned node changes, so failed batches are retried
    by the same event-driven path that drives the unbatched mode.
    """

    def __init__(
        self,
        controller: PodController,
        *,
        timeout: float,
        idle: float,
    ) -> None:
        self.name = "tpu-pod-batch-planner"
        self._controller = controller
        self._batcher: Batcher[Request] | None = (
            Batcher(timeout=timeout, idle=idle) if idle > 0 else None
        )
        self._queue: "queue.Queue[Request]" = queue.Queue()
        self._stop = threading.Event()
        # Serializes planning across worker generations: stop() joins
        # with a timeout, so a leader-election stop/start cycle can
        # briefly overlap an old worker finishing its batch with the new
        # one — the lock keeps the single-planner invariant
        # (max_concurrent=1) either way.
        self._plan_lock = threading.Lock()
        self._thread: threading.Thread | None = None

    def reconcile(self, request: Request) -> Result:
        """The Controller-facing reconciler: enqueue and return."""
        if self._batcher is not None:
            self._batcher.add(request)
        else:
            self._queue.put(request)
        return Result()

    def _next_batch(self) -> list[Request]:
        """Blocks (briefly) for the next batch in the active mode."""
        if self._batcher is not None:
            return self._batcher.get_batch(timeout=0.2)
        batch = [self._queue.get(timeout=0.2)]
        while True:
            try:
                batch.append(self._queue.get_nowait())
            except queue.Empty:
                return batch

    def _run(self, stop: threading.Event) -> None:
        while not stop.is_set():
            try:
                batch = self._next_batch()
            except queue.Empty:
                continue
            try:
                with self._plan_lock:
                    self._controller.reconcile_batch(batch)
            except Exception:
                logger.exception(
                    "pod controller: batch of %d requests failed; the "
                    "node-event mapper will re-enqueue still-pending pods",
                    len(batch),
                )

    def start(self) -> None:
        # Fresh stop event per generation: the previous stop() set the
        # old one, and a worker that outlived its join timeout must keep
        # seeing it set rather than be resurrected by a clear().
        self._stop = threading.Event()
        if self._batcher is not None:
            self._batcher.start()
        self._thread = threading.Thread(
            target=self._run, args=(self._stop,), daemon=True,
            name="pod-batch-planner",
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._batcher is not None:
            self._batcher.stop()
        if self._thread:
            self._thread.join(timeout=2.0)

    # Registered on the Manager like a controller (duck-typed start/stop)
    # so leader-election stop/start cycles restart the batch worker too.
    close = stop

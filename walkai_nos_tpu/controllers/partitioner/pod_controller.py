"""Pod controller: pending pod -> repartitioned node (the core loop).

Port of `internal/controllers/gpupartitioner/mig_controller.go:35-213`:
for a pending+unschedulable pod requesting `walkai.io/tpu-<shape>` slices,
list tiling-partitioned nodes; if no node already exposes the wanted
profiles free, walk nodes first-fit and try a geometry transition; on
success write the new spec annotations + plan ID. Single-threaded
(MaxConcurrentReconciles=1, `mig_controller.go:204`) so concurrent pending
pods can't race partitioning decisions.

Retry is event-driven, like the reference's watch mapping
(`mig_controller.go:180-207`): a decision is a pure function of pod + node
state, so a failed attempt is only worth repeating when a partitioned
node actually changed — `make_node_event_mapper` re-enqueues every pending
slice pod on node add/annotation-change events instead of polling.
"""

from __future__ import annotations

import logging
from typing import Callable

from walkai_nos_tpu.api import constants
from walkai_nos_tpu.kube import objects
from walkai_nos_tpu.kube.client import KubeClient, NotFound
from walkai_nos_tpu.kube.runtime import Request, Result
from walkai_nos_tpu.partitioning.partitioner import Partitioner
from walkai_nos_tpu.partitioning.plan_id import new_partitioning_plan_id
from walkai_nos_tpu.partitioning.state import build_node_partitioning
from walkai_nos_tpu.tpu.partitioning import Geometry, PartitioningKind
from walkai_nos_tpu.tpu.sharing.node import SharingNode
from walkai_nos_tpu.tpu.sharing.profile import get_requested_shared_profiles
from walkai_nos_tpu.tpu.tiling.node import Node
from walkai_nos_tpu.tpu.tiling.profile import get_requested_profiles

logger = logging.getLogger(__name__)


def make_node_event_mapper(
    kube: KubeClient, enqueue: Callable[[Request], None]
) -> Callable[[Request], Result]:
    """Node events -> pending-slice-pod reconciles.

    The analogue of the reference's `Watches(&corev1.Node{},
    handler.EnqueueRequestsFromMapFunc(...))` wiring
    (`mig_controller.go:180-207`): whenever a partitioned node is added or
    its annotations change (capacity freed, a retile reported, a plan
    acked), every pod that re-tiling could still help is re-enqueued on
    the pod controller's queue. This replaces periodic pending-pod polling
    — with no node change, a retry would recompute the same answer."""

    def reconcile(_request: Request) -> Result:
        for pod in kube.list("Pod"):
            if not objects.extra_resources_could_help_scheduling(pod):
                continue
            if not get_requested_profiles(pod) and not (
                get_requested_shared_profiles(pod)
            ):
                continue
            enqueue(
                Request(
                    name=objects.name(pod), namespace=objects.namespace(pod)
                )
            )
        return Result()

    return reconcile


class PodController:
    def __init__(
        self,
        kube: KubeClient,
        partitioner: Partitioner | None = None,
        plan_id_fn: Callable[[], str] = new_partitioning_plan_id,
    ) -> None:
        self._kube = kube
        self._partitioner = partitioner or Partitioner(kube)
        # Injectable plan-ID generator (test seam, `mig_controller.go:209-213`).
        self._plan_id_fn = plan_id_fn

    # ------------------------------------------------------------- reconcile

    def reconcile(self, request: Request) -> Result:
        try:
            pod = self._kube.get("Pod", request.name, request.namespace or None)
        except NotFound:
            return Result()
        if not self._should_consider_pod(pod):
            return Result()
        wanted = get_requested_profiles(pod)
        if wanted:
            nodes = self._list_tiling_nodes()
            if not self._profiles_already_available(nodes, wanted):
                # Otherwise the scheduler will bind the pod on its next
                # cycle (`mig_controller.go:121-144`); its binding flips
                # node usage, which flows back as a status-annotation
                # event if anything else is still pending.
                self._try_repartition(nodes, wanted, pod)
        # Dynamic sharing: the capability the reference fork reduced to
        # report-only (upstream nos planned MPS layouts alongside MIG);
        # chip-count shares are planned the same way against
        # sharing-labeled nodes.
        wanted_shared = get_requested_shared_profiles(pod)
        if wanted_shared:
            nodes = self._list_sharing_nodes()
            if not self._shared_profiles_already_available(
                nodes, wanted_shared
            ):
                self._try_reshare(nodes, wanted_shared, pod)
        return Result()

    # --------------------------------------------------------------- helpers

    def _should_consider_pod(self, pod: dict) -> bool:
        """Re-tiling only helps pods that new slice resources could
        schedule (`mig_controller.go:100-111` ->
        `ExtraResourcesCouldHelpScheduling`, `pkg/util/pod/pod.go:28-35`):
        pending + unschedulable, not mid-preemption, and not node-bound by
        ownership (DaemonSet/static pods follow their node, not resources)."""
        return objects.extra_resources_could_help_scheduling(pod)

    def _list_tiling_nodes(self) -> list[dict]:
        return self._kube.list(
            "Node",
            label_selector={
                constants.LABEL_TPU_PARTITIONING: PartitioningKind.TILING.value
            },
        )

    def _list_sharing_nodes(self) -> list[dict]:
        return self._kube.list(
            "Node",
            label_selector={
                constants.LABEL_TPU_PARTITIONING: PartitioningKind.SHARING.value
            },
        )

    def _shared_profiles_already_available(
        self, nodes: list[dict], wanted: Geometry
    ) -> bool:
        return self._available(nodes, wanted, SharingNode.from_node)

    def _try_reshare(
        self, nodes: list[dict], wanted: Geometry, pod: dict
    ) -> bool:
        """First-fit share planning over sharing nodes — the sharing twin
        of `_try_repartition`, using the chip-count model
        (`tpu/sharing/mesh.py` two-phase search)."""
        return self._first_fit(
            nodes, wanted, pod, SharingNode.from_node, "re-shared"
        )

    def _profiles_already_available(
        self, nodes: list[dict], wanted: Geometry
    ) -> bool:
        return self._available(nodes, wanted, Node.from_node)

    def _available(
        self, nodes: list[dict], wanted: Geometry, node_factory
    ) -> bool:
        for node_obj in nodes:
            node = node_factory(
                objects.name(node_obj),
                objects.labels(node_obj),
                objects.annotations(node_obj),
            )
            if node.provides_profiles(wanted):
                return True
        return False

    def _try_repartition(
        self, nodes: list[dict], wanted: Geometry, pod: dict
    ) -> bool:
        """First-fit over candidate nodes (`mig_controller.go:146-207`)."""
        return self._first_fit(
            nodes, wanted, pod, Node.from_node, "repartitioned"
        )

    def _first_fit(
        self, nodes: list[dict], wanted: Geometry, pod: dict, node_factory,
        verb: str,
    ) -> bool:
        """The first-fit planning loop shared by tiling and sharing: both
        node models expose the same search surface (has_free_capacity /
        clone / update_geometry_for / provides_profiles)."""
        for node_obj in nodes:
            node = node_factory(
                objects.name(node_obj),
                objects.labels(node_obj),
                objects.annotations(node_obj),
            )
            if not node.has_free_capacity():
                continue
            candidate = node.clone()
            if not candidate.update_geometry_for(wanted):
                continue
            if not candidate.provides_profiles(wanted):
                continue
            plan_id = self._plan_id_fn()
            self._partitioner.apply_partitioning(
                node_obj, build_node_partitioning(candidate), plan_id
            )
            logger.info(
                "pod controller: %s node %s for pod %s/%s "
                "(wanted %s, plan %s)",
                verb,
                node.name,
                objects.namespace(pod),
                objects.name(pod),
                wanted,
                plan_id,
            )
            return True
        logger.info(
            "pod controller: no node can provide %s for pod %s/%s",
            wanted,
            objects.namespace(pod),
            objects.name(pod),
        )
        return False

"""Node controller: initialize freshly labeled TPU nodes.

Port of `internal/controllers/gpupartitioner/node_controller.go:36-115`:
watches nodes carrying the partitioning label; a node whose meshes carry no
spec annotations yet is uninitialized (the reference compares GFD GPU count
with annotated GPU count, `node_controller.go:90-97`) and gets the default
fewest-slices tiling.
"""

from __future__ import annotations

import logging

from walkai_nos_tpu.api import constants
from walkai_nos_tpu.kube import objects
from walkai_nos_tpu.kube.client import ApiError, KubeClient, NotFound
from walkai_nos_tpu.kube.runtime import Request, Result
from walkai_nos_tpu.partitioning.initializer import NodeInitializer
from walkai_nos_tpu.tpu import topology
from walkai_nos_tpu.tpu.annotations import parse_node_annotations
from walkai_nos_tpu.tpu.partitioning import is_tiling_partitioning_enabled

logger = logging.getLogger(__name__)


class NodeController:
    def __init__(self, kube: KubeClient, initializer: NodeInitializer | None = None):
        self._kube = kube
        self._initializer = initializer or NodeInitializer(kube)
        # Nodes already refused for multi-host topology: without this,
        # every node MODIFIED event re-logs the warning and re-attempts
        # the (409) event create for the node's whole lifetime.
        self._refused_multi_host: set[str] = set()

    def reconcile(self, request: Request) -> Result:
        try:
            node = self._kube.get("Node", request.name)
        except NotFound:
            return Result()
        if not is_tiling_partitioning_enabled(objects.labels(node)):
            return Result()
        labels = objects.labels(node)
        if topology.is_multi_host(labels):
            pool_topo = topology.get_pool_topology(labels)
            if (
                pool_topo is None
                or topology.pool_key(labels) is None
                or topology.worker_id(labels) is None
            ):
                # Not coordinatable: topology the host mesh does not
                # evenly tile, no pool-membership label to group by, or
                # no worker-id giving the host's physical grid position
                # (guessing it could hand out a slice with no ICI torus
                # behind it — see PoolNode.from_nodes).
                self._refuse_multi_host(node)
                return Result()
            if self._pool_member_initialized(node):
                return Result()
            logger.info(
                "node controller: initializing pool member %s "
                "(pool %s, share %s)",
                request.name,
                topology.pool_key(labels),
                pool_topo.pool_profile,
            )
            self._initializer.init_pool_member(node, pool_topo)
            return Result()
        if self._is_initialized(node):
            return Result()
        logger.info("node controller: initializing node %s", request.name)
        self._initializer.init_node_partitioning(node)
        return Result()

    def _pool_member_initialized(self, node: dict) -> bool:
        _, spec = parse_node_annotations(objects.annotations(node))
        return bool(spec)

    def _refuse_multi_host(self, node: dict) -> None:
        """Multi-host pool labeled for partitioning: refuse loudly (event +
        log) and leave the node schedulable as a whole slice. Deterministic
        event name makes the refusal idempotent across reconciles."""
        name = objects.name(node)
        _, spec = parse_node_annotations(objects.annotations(node))
        if name in self._refused_multi_host and not spec:
            return  # settled: already refused, nothing left to clean
        topo = objects.labels(node).get(constants.LABEL_TPU_TOPOLOGY, "")
        logger.warning(
            "node controller: node %s has multi-host topology %s; "
            "refusing to partition (schedule it whole)", name, topo,
        )
        # A node partitioned before it was recognized as multi-host (or
        # relabeled into a multi-host pool) must stop being actuated:
        # clear any lingering spec annotations so the agent tears nothing
        # and the node really is whole.
        if spec:
            updates: dict[str, str | None] = {a.key: None for a in spec}
            updates[constants.ANNOTATION_PARTITIONING_PLAN] = None
            self._kube.patch(
                "Node", name, {"metadata": {"annotations": updates}}
            )
        event = {
            "metadata": {"name": f"{name}.multi-host-topology"},
            "involvedObject": {"kind": "Node", "name": name},
            "reason": "MultiHostTopology",
            "type": "Warning",
            "message": (
                f"topology {topo} spans hosts; dynamic partitioning is "
                "host-local — the node stays schedulable as a whole slice"
            ),
        }
        try:
            self._kube.create("Event", event, namespace="default")
        except ApiError as e:
            if e.status != 409:
                # Transient failure: leave the node un-memoized so the
                # next reconcile retries the (idempotently named) event.
                logger.warning(
                    "node controller: could not emit MultiHostTopology "
                    "event for %s: %s", name, e,
                )
                return
        self._refused_multi_host.add(name)

    def _is_initialized(self, node: dict) -> bool:
        """Mesh count == number of spec-annotated meshes
        (`node_controller.go:90-97` `isNodeInitialized`)."""
        model = topology.get_model(objects.labels(node))
        if model is None:
            return True  # nothing to initialize
        _, spec = parse_node_annotations(objects.annotations(node))
        annotated_meshes = {s.mesh_index for s in spec}
        return len(annotated_meshes) >= 1  # one mesh per host

"""tpuagent: the per-node DaemonSet agent (reporter + actuator).

Analogue of `internal/controllers/migagent/`: the reporter writes observed
slice state into `status-tpu-*` node annotations; the actuator diffs
`spec-tpu-*` against status, plans create/delete operations, actuates them
through tpudev, and restarts the device plugin — with the same
report-before-apply handshake, plan-ID acking, delete-free-only rule, and
rollback-on-failed-create semantics.
"""

from walkai_nos_tpu.controllers.tpuagent.plan import (  # noqa: F401
    CreateOperation,
    DeleteOperation,
    TilingPlan,
    TilingState,
)
from walkai_nos_tpu.controllers.tpuagent.shared import SharedState  # noqa: F401
from walkai_nos_tpu.controllers.tpuagent.reporter import Reporter  # noqa: F401
from walkai_nos_tpu.controllers.tpuagent.actuator import Actuator  # noqa: F401

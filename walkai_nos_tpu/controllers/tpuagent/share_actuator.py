"""Share actuator: node spec annotations -> advertised share devices.

The sharing twin of the tiling Actuator, radically simpler because a
share needs no device-layer materialization: the spec IS the durable
desired state, and "applying" it means handing the geometry to the
share plugin manager (which re-advertises to the kubelet). The plan-ID
ack protocol is kept so the partitioner sees the same
spec/status/plan handshake on both node kinds.
"""

from __future__ import annotations

import logging

from walkai_nos_tpu.api import constants
from walkai_nos_tpu.controllers.tpuagent.shared import SharedState
from walkai_nos_tpu.kube import objects
from walkai_nos_tpu.kube.client import KubeClient
from walkai_nos_tpu.kube.runtime import Request, Result
from walkai_nos_tpu.tpu.annotations import parse_node_annotations
from walkai_nos_tpu.tpu.errors import GenericError
from walkai_nos_tpu.tpu.partitioning import Geometry
from walkai_nos_tpu.tpu.sharing.profile import SharedProfile

logger = logging.getLogger(__name__)


class ShareActuator:
    def __init__(
        self,
        kube: KubeClient,
        shared_state: SharedState,
        node_name: str,
        share_manager,
        sharing_client=None,
    ) -> None:
        self._kube = kube
        self._shared = shared_state
        self._node_name = node_name
        self._manager = share_manager
        # Ground truth for pinning: kubelet-reported used share devices
        # may never lose or change their chips (the sharing twin of the
        # tiling rule that used slices are never moved).
        self._sharing_client = sharing_client

    def _pinned_ids(self) -> set[str]:
        if self._sharing_client is None:
            return set()
        from walkai_nos_tpu.tpu.sharing.client import extract_shared_device_id

        # Strip the device-plugin replica suffix ("2c#0::1" -> "2c#0"):
        # assigner share IDs never carry it, and an unmatched pin is a
        # silently unprotected allocation.
        return {
            extract_shared_device_id(d.device_id)
            for d in self._sharing_client.get_tpu_devices().get_used()
        }

    def reconcile(self, request: Request) -> Result:
        node = self._kube.get("Node", self._node_name)
        ann = objects.annotations(node)
        plan_id = ann.get(constants.ANNOTATION_PARTITIONING_PLAN)
        _, spec = parse_node_annotations(ann)
        geometry: Geometry = {}
        for s in spec:
            try:
                SharedProfile.parse(s.profile)
            except ValueError:
                continue  # tiling profile on a sharing node: not ours
            geometry[s.profile] = geometry.get(s.profile, 0) + s.quantity
        # Non-destructive apply: no report-before-apply gating needed, so
        # the latch is left alone — only the plan-ID ack flows through.
        try:
            self._manager.set_geometry(geometry, self._pinned_ids())
        except GenericError as e:
            # Oversized/invalid spec (e.g. labels disagree with the real
            # host): keep the previous advertisement, do NOT ack the plan
            # (an acked-but-unrealized plan would feed replan churn), and
            # say so; the reporter's status keeps showing reality.
            logger.warning(
                "share actuator: node %s spec %s not applicable: %s",
                self._node_name,
                geometry,
                e,
            )
            return Result(requeue_after=5.0)
        # Ack only applied plans.
        self._shared.last_parsed_plan_id = plan_id
        return Result()

"""Actuator: spec annotations -> actual TPU slices.

Port of `internal/controllers/migagent/actuator.go:36-310` with the
placement-permutation search replaced by deterministic mesh packing:

- gate on the reporter handshake (`actuator.go:75-78`);
- record the spec plan ID for the reporter to ack (`:90`);
- done when spec matches status (`:94`) or when the same (plan, status)
  pair was already applied (`:113-116`);
- plan via the pure diff planner; a NotFound from the device boundary means
  the kubelet advertises a stale device -> restart the device plugin
  instead of failing (`:135-138`);
- apply deletes first (free devices only), then pack + create; roll back
  deletions if creates fail (`:287`); restart the device plugin when
  devices changed (`:210`).
"""

from __future__ import annotations

import logging

from walkai_nos_tpu.api import constants
from walkai_nos_tpu.controllers.tpuagent.plan import (
    TilingPlan,
    TilingState,
    new_tiling_plan,
)
from walkai_nos_tpu.controllers.tpuagent.shared import SharedState
from walkai_nos_tpu.kube import objects
from walkai_nos_tpu.kube.client import KubeClient
from walkai_nos_tpu.kube.runtime import Request, Result
from walkai_nos_tpu.tpu import topology as topo
from walkai_nos_tpu.tpu.annotations import (
    SpecAnnotation,
    StatusAnnotation,
    parse_node_annotations,
    spec_matches_status,
)
from walkai_nos_tpu.tpu.errors import GenericError, TpuError
from walkai_nos_tpu.tpu.tiling.client import DevicePluginClient, TilingClient
from walkai_nos_tpu.tpu.tiling.packing import Placement, pack_geometry
from walkai_nos_tpu.tpudev.client import SliceInfo

logger = logging.getLogger(__name__)


def placement_from_slice_info(info: SliceInfo, host) -> Placement:
    """Reconstruct a Placement from a materialized slice's chip coords."""
    chip_by_id = {c.chip_id: c for c in host.chips}
    coords = [chip_by_id[cid].coords for cid in info.chip_ids]
    lo = tuple(min(c[d] for c in coords) for d in range(len(host.mesh)))
    hi = tuple(max(c[d] for c in coords) for d in range(len(host.mesh)))
    orientation = tuple(h - l + 1 for l, h in zip(lo, hi))
    return Placement(profile=info.profile, offset=lo, orientation=orientation)


class Actuator:
    def __init__(
        self,
        kube: KubeClient,
        tiling_client: TilingClient,
        device_plugin: DevicePluginClient,
        shared_state: SharedState,
        node_name: str,
    ) -> None:
        self._kube = kube
        self._client = tiling_client
        self._plugin = device_plugin
        self._shared = shared_state
        self._node_name = node_name
        self._last_applied: tuple[str | None, frozenset] | None = None

    # ------------------------------------------------------------- reconcile

    def reconcile(self, request: Request) -> Result:
        if not self._shared.at_least_one_report_since_last_apply():
            return Result(requeue_after=1.0)

        node = self._kube.get("Node", self._node_name)
        ann = objects.annotations(node)
        plan_id = ann.get(constants.ANNOTATION_PARTITIONING_PLAN)

        status, spec = parse_node_annotations(ann)
        if spec_matches_status(spec, status):
            # Converged: the plan is realized, ack it.
            self._shared.last_parsed_plan_id = plan_id
            return Result()

        applied_key = (plan_id, frozenset(status))
        if self._last_applied == applied_key:
            # Already actuated this exact (plan, observed-state) pair; wait
            # for the reporter to move status (`actuator.go:113-116`).
            return Result()

        plan = self._plan(spec)
        if plan is None:  # stale device -> plugin restarted instead
            return Result(requeue_after=1.0)
        if plan.is_empty():
            self._shared.last_parsed_plan_id = plan_id
            return Result()
        logger.info("actuator: node %s applying plan %s",
                    self._node_name, plan.summary())
        self._apply(plan)
        # Ack only plans that actually actuated: a failed apply must not
        # be echoed into status-partitioning-plan, or the partitioner
        # would take an unrealized plan as acknowledged and keep minting
        # fresh plan IDs against it (ack-write -> replan churn).
        self._shared.last_parsed_plan_id = plan_id
        self._last_applied = applied_key
        self._shared.on_apply_done()
        return Result()

    # ------------------------------------------------------------------ plan

    def _plan(self, spec: list[SpecAnnotation]) -> TilingPlan | None:
        try:
            devices = self._client.get_tpu_devices()
        except TpuError as e:
            if e.is_not_found():
                # kubelet advertises a device tpudev doesn't know: restart
                # the plugin to resync (`actuator.go:135-138`).
                logger.warning(
                    "actuator: stale device on %s (%s); restarting device plugin",
                    self._node_name,
                    e,
                )
                self._plugin.restart(self._node_name)
                return None
            raise
        # Symmetric staleness: tpudev knows slices the kubelet does NOT
        # advertise (e.g. a crash between slice creation and device-plugin
        # re-registration). Planning against the stale kubelet view would
        # double-create; restart the plugin to resync instead.
        known = {d.device_id for d in devices}
        materialized = {s.slice_id for s in self._client.list_slices()}
        if materialized - known:
            logger.warning(
                "actuator: %d slice(s) on %s not advertised by kubelet (%s); "
                "restarting device plugin",
                len(materialized - known),
                self._node_name,
                sorted(materialized - known),
            )
            self._plugin.restart(self._node_name)
            return None
        state = TilingState.from_devices(devices)
        return new_tiling_plan(state, spec)

    # ----------------------------------------------------------------- apply

    def _apply(self, plan: TilingPlan) -> None:
        host = self._client.get_topology()
        deleted: list[SliceInfo] = []
        changed = False
        slice_by_id = {s.slice_id: s for s in self._client.list_slices()}

        # Deletes first, free devices only (`actuator.go:216-261`).
        delete_errors: list[str] = []
        for op in plan.delete_ops:
            remaining = op.quantity
            for device in op.candidates:
                if remaining == 0:
                    break
                if not device.is_free():
                    continue  # never delete a used device
                info = slice_by_id.get(device.device_id)
                try:
                    self._client.delete_slice(device.device_id)
                except TpuError as e:
                    if e.is_not_found():
                        remaining -= 1  # already gone counts as deleted
                        continue
                    delete_errors.append(f"{device.device_id}: {e}")
                    continue
                if info is not None:
                    deleted.append(info)
                remaining -= 1
                changed = True
            if remaining > 0:
                delete_errors.append(
                    f"mesh {op.mesh_index} {op.profile}: "
                    f"{remaining} device(s) could not be deleted"
                )

        # Creates via packing (`actuator.go:263-309`, packing replaces the
        # NVML permutation loop).
        try:
            created = self._apply_create_ops(plan, host)
            changed = changed or bool(created)
        except GenericError:
            self._rollback_deleted(deleted)
            raise

        if delete_errors:
            raise GenericError("; ".join(delete_errors))

        if changed:
            self._plugin.restart(self._node_name)

    def _apply_create_ops(self, plan: TilingPlan, host) -> list[SliceInfo]:
        if not plan.create_ops:
            return []
        created: list[SliceInfo] = []
        by_mesh: dict[int, list] = {}
        for op in plan.create_ops:
            by_mesh.setdefault(op.mesh_index, []).append(op)
        for mesh_index, ops in sorted(by_mesh.items()):
            existing = [
                s
                for s in self._client.list_slices()
                if s.mesh_index == mesh_index
            ]
            pinned = [placement_from_slice_info(s, host) for s in existing]
            # A profile spanning more chips than this host holds is this
            # host's SHARE of a pool-level (multi-host) slice: it
            # occupies the entire host mesh, advertised under the pool
            # profile's resource name (tpu/tiling/pool.py).
            pool_ops = [
                op for op in ops
                if topo.shape_chip_count(topo.parse_shape(op.profile))
                > host.chip_count
            ]
            local_ops = [op for op in ops if op not in pool_ops]
            if pool_ops:
                if (
                    pinned
                    or local_ops
                    or len(pool_ops) > 1
                    or pool_ops[0].quantity != 1
                ):
                    raise GenericError(
                        f"mesh {mesh_index}: a pool share occupies the "
                        f"whole host; spec mixes it with other slices "
                        f"({[o.profile for o in ops]}, "
                        f"{len(pinned)} existing)"
                    )
                share = Placement(
                    profile=pool_ops[0].profile,
                    offset=(0,) * len(host.mesh),
                    orientation=host.mesh,
                )
                result = self._client.create_slices([share])
                created.extend(result)
                if not result:
                    raise GenericError(
                        f"mesh {mesh_index}: pool share "
                        f"{pool_ops[0].profile} not created"
                    )
                continue
            geometry: dict[str, int] = {}
            for p in pinned:
                geometry[p.profile] = geometry.get(p.profile, 0) + 1
            for op in ops:
                geometry[op.profile] = geometry.get(op.profile, 0) + op.quantity
            placements = pack_geometry(host.mesh, geometry, pinned)
            if placements is None:
                raise GenericError(
                    f"mesh {mesh_index}: geometry {geometry} not placeable "
                    f"with {len(pinned)} pinned slice(s)"
                )
            new_placements = placements[len(pinned):]
            result = self._client.create_slices(new_placements)
            created.extend(result)
            if len(result) < len(new_placements):
                raise GenericError(
                    f"mesh {mesh_index}: created only {len(result)}/"
                    f"{len(new_placements)} slices"
                )
        return created

    def _rollback_deleted(self, deleted: list[SliceInfo]) -> None:
        """Re-create slices deleted earlier in a failed apply
        (`actuator.go:287-296`)."""
        if not deleted:
            return
        host = self._client.get_topology()
        placements = [placement_from_slice_info(s, host) for s in deleted]
        try:
            self._client.create_slices(placements)
        except TpuError as e:
            logger.error(
                "actuator: rollback of %d deleted slice(s) failed: %s",
                len(deleted),
                e,
            )

    # ------------------------------------------------------------- test seam

    def last_applied_status(self) -> frozenset[StatusAnnotation] | None:
        return self._last_applied[1] if self._last_applied else None

"""Reporter: observed devices -> status annotations on the node.

Port of `internal/controllers/migagent/reporter.go:34-123`: read ground
truth through the tiling client, fold into `status-tpu-*` annotations, diff
against the node, and patch — replacing *all* previous status annotations —
plus echo `status-partitioning-plan` = the last plan ID the actuator
parsed. Requeues on a fixed refresh interval so drift is always healed.
"""

from __future__ import annotations

import logging

from walkai_nos_tpu.api import constants
from walkai_nos_tpu.controllers.tpuagent.shared import SharedState
from walkai_nos_tpu.kube import objects
from walkai_nos_tpu.kube.client import KubeClient
from walkai_nos_tpu.kube.runtime import Request, Result
from walkai_nos_tpu.tpu.annotations import parse_node_annotations
from walkai_nos_tpu.tpu.tiling.client import TilingClient
from walkai_nos_tpu.tpu.tiling.profile import extract_profile_name

logger = logging.getLogger(__name__)


class Reporter:
    def __init__(
        self,
        kube: KubeClient,
        tiling_client: TilingClient,
        shared_state: SharedState,
        node_name: str,
        refresh_interval: float = constants.DEFAULT_AGENT_REPORT_INTERVAL_S,
        profile_extractor=extract_profile_name,
    ) -> None:
        self._kube = kube
        self._client = tiling_client
        self._shared = shared_state
        self._node_name = node_name
        self._interval = refresh_interval
        # Resource-name -> profile mapping; the sharing agent reuses this
        # reporter with the shared-profile extractor (the gpuagent reporter
        # is structurally identical to the migagent one, `gpuagent/reporter.go`).
        self._extract_profile = profile_extractor

    def reconcile(self, request: Request) -> Result:
        with self._shared.lock:
            try:
                return self._reconcile(request)
            finally:
                # Even a failed report observed the world; the actuator gate
                # only needs *a* report attempt after its last apply
                # (`reporter.go:60-62` defers OnReportDone under the lock).
                self._shared.on_report_done()

    def _reconcile(self, request: Request) -> Result:
        node = self._kube.get("Node", self._node_name)
        devices = self._client.get_tpu_devices()
        status_annotations = devices.as_status_annotations(self._extract_profile)

        current_status, _ = parse_node_annotations(objects.annotations(node))
        plan_ack = objects.annotations(node).get(
            constants.ANNOTATION_REPORTED_PARTITIONING_PLAN
        )
        desired_ack = self._shared.last_parsed_plan_id

        if set(status_annotations) == set(current_status) and plan_ack == desired_ack:
            return Result(requeue_after=self._interval)

        # Replace ALL status annotations (`reporter.go:89-103`): build a
        # merge patch that nulls stale keys and writes fresh ones.
        updates: dict[str, str | None] = {
            ann.key: None
            for ann in current_status
        }
        for ann in status_annotations:
            updates[ann.key] = ann.value
        updates[constants.ANNOTATION_REPORTED_PARTITIONING_PLAN] = desired_ack
        self._kube.patch(
            "Node", self._node_name, objects.annotation_patch(updates)
        )
        logger.info(
            "reporter: node %s status updated (%d annotations, plan=%s)",
            self._node_name,
            len(status_annotations),
            desired_ack,
        )
        return Result(requeue_after=self._interval)

"""Node-local diff planner: (observed devices, desired spec) -> operations.

Pure port of the semantics of `internal/controllers/migagent/plan/`
(`plan.go:31-139`, `mig_state.go`, `operation.go`):

- delete devices whose profile/quantity exceeds the spec, preferring *free*
  devices as candidates (used ones are listed but the actuator only ever
  deletes free devices);
- create devices the spec wants but the node lacks;
- when any create op exists on a mesh, every existing free device on that
  mesh is deleted and re-created too, giving the placement engine the whole
  free area to work with (`plan.go:81-89` — the reference does this to
  maximize NVML placement permutations; here it maximizes contiguous room
  for the packer).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from walkai_nos_tpu.tpu.annotations import SpecAnnotation
from walkai_nos_tpu.tpu.device import Device, DeviceList
from walkai_nos_tpu.tpu.tiling.profile import extract_profile_name


class TilingState(dict):
    """mesh index -> DeviceList (`mig_state.go:24-87` `MigState`)."""

    @staticmethod
    def from_devices(devices: DeviceList) -> "TilingState":
        state = TilingState()
        for idx, devs in devices.group_by_mesh_index().items():
            state[idx] = devs
        return state

    def matches_spec(self, spec: list[SpecAnnotation]) -> bool:
        """Order-insensitive equality of (mesh, profile) -> qty
        (`mig_state.go:42-66` `Matches`)."""
        desired: dict[tuple[int, str], int] = {}
        for s in spec:
            if s.quantity > 0:
                key = (s.mesh_index, s.profile)
                desired[key] = desired.get(key, 0) + s.quantity
        actual: dict[tuple[int, str], int] = {}
        for idx, devs in self.items():
            for d in devs:
                key = (idx, extract_profile_name(d.resource_name))
                actual[key] = actual.get(key, 0) + 1
        return desired == actual


@dataclass(frozen=True)
class CreateOperation:
    """Create `quantity` slices of `profile` on mesh `mesh_index`
    (`operation.go:25-38`)."""

    mesh_index: int
    profile: str
    quantity: int


@dataclass(frozen=True)
class DeleteOperation:
    """Delete `quantity` devices among `candidates` (free ones only get
    actuated — `operation.go:40-54` + `actuator.go:216-261`)."""

    mesh_index: int
    profile: str
    candidates: tuple[Device, ...]
    quantity: int


@dataclass
class TilingPlan:
    create_ops: list[CreateOperation] = field(default_factory=list)
    delete_ops: list[DeleteOperation] = field(default_factory=list)

    def is_empty(self) -> bool:
        return not self.create_ops and not self.delete_ops

    def summary(self) -> str:
        return (
            "create="
            f"{[(o.mesh_index, o.profile, o.quantity) for o in self.create_ops]} "
            f"delete={[(o.mesh_index, o.profile, o.quantity) for o in self.delete_ops]}"
        )


def new_tiling_plan(state: TilingState, spec: list[SpecAnnotation]) -> TilingPlan:
    """Compute the ops turning `state` into `spec` (`plan.go:31-92`)."""
    plan = TilingPlan()

    desired: dict[int, dict[str, int]] = {}
    for s in spec:
        if s.quantity > 0:
            desired.setdefault(s.mesh_index, {})
            desired[s.mesh_index][s.profile] = (
                desired[s.mesh_index].get(s.profile, 0) + s.quantity
            )

    actual: dict[int, dict[str, DeviceList]] = {}
    for idx, devs in state.items():
        actual[idx] = {}
        for d in devs:
            actual[idx].setdefault(extract_profile_name(d.resource_name), DeviceList())
            actual[idx][extract_profile_name(d.resource_name)].append(d)

    mesh_indices = sorted(set(desired) | set(actual))
    meshes_with_creates: set[int] = set()

    # Pass 1: quantity diffs.
    for idx in mesh_indices:
        profiles = sorted(
            set(desired.get(idx, {})) | set(actual.get(idx, {}))
        )
        for profile in profiles:
            want = desired.get(idx, {}).get(profile, 0)
            have_devices = actual.get(idx, {}).get(profile, DeviceList())
            have = len(have_devices)
            if want > have:
                plan.create_ops.append(
                    CreateOperation(idx, profile, want - have)
                )
                meshes_with_creates.add(idx)
            elif have > want:
                plan.delete_ops.append(
                    DeleteOperation(
                        idx,
                        profile,
                        candidates=tuple(
                            _deletion_candidates(have_devices)
                        ),
                        quantity=have - want,
                    )
                )

    # Pass 2: re-create free devices on meshes with creates (`plan.go:81-89`),
    # excluding devices already fully scheduled for deletion.
    doomed: dict[int, dict[str, int]] = {}
    for op in plan.delete_ops:
        doomed.setdefault(op.mesh_index, {})[op.profile] = op.quantity
    extra_deletes: list[DeleteOperation] = []
    extra_creates: list[CreateOperation] = []
    for idx in sorted(meshes_with_creates):
        for profile, devices in sorted(actual.get(idx, {}).items()):
            already_doomed = doomed.get(idx, {}).get(profile, 0)
            free = devices.get_free()
            recreate = len(free) - already_doomed
            if recreate <= 0:
                continue
            extra_deletes.append(
                DeleteOperation(
                    idx,
                    profile,
                    candidates=tuple(_deletion_candidates(devices)),
                    quantity=len(free),  # all free devices go
                )
            )
            extra_creates.append(CreateOperation(idx, profile, recreate))
    # Merge: an extra delete op for a (mesh, profile) replaces the pass-1 op
    # (it covers a superset of the quantity).
    for ed in extra_deletes:
        plan.delete_ops = [
            op
            for op in plan.delete_ops
            if (op.mesh_index, op.profile) != (ed.mesh_index, ed.profile)
        ]
        plan.delete_ops.append(ed)
    plan.create_ops.extend(extra_creates)

    plan.create_ops.sort(key=lambda o: (o.mesh_index, o.profile))
    plan.delete_ops.sort(key=lambda o: (o.mesh_index, o.profile))
    return plan


def _deletion_candidates(devices: DeviceList) -> DeviceList:
    """Free devices first, deterministic within each group
    (`plan.go:111-139` `extractCandidatesForDeletion`)."""
    return DeviceList(
        devices.get_free().sorted_by_device_id()
        + devices.get_used().sorted_by_device_id()
    )

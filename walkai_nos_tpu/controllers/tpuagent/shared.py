"""Reporter/Actuator handshake state.

Port of `internal/controllers/migagent/shared.go:24-57`: a mutex plus a
"report happened since the last apply" latch. The actuator refuses to act
on state the reporter hasn't refreshed since the previous actuation —
otherwise it would re-plan against a stale status and thrash the devices.
Also carries the last plan ID the actuator parsed, which the reporter
echoes into `status-partitioning-plan` as the ack.
"""

from __future__ import annotations

import threading


class SharedState:
    def __init__(self) -> None:
        self.lock = threading.RLock()
        self._report_since_apply = threading.Event()
        self._last_parsed_plan_id: str | None = None

    # -------------------------------------------------------------- handshake

    def on_report_done(self) -> None:
        """Reporter finished a cycle (`shared.go:36-41`)."""
        self._report_since_apply.set()

    def on_apply_done(self) -> None:
        """Actuator finished an apply; require a fresh report before the
        next one (`shared.go:43-48`)."""
        self._report_since_apply.clear()

    def at_least_one_report_since_last_apply(self) -> bool:
        """`shared.go:50-57`."""
        return self._report_since_apply.is_set()

    # --------------------------------------------------------------- plan ids

    @property
    def last_parsed_plan_id(self) -> str | None:
        with self.lock:
            return self._last_parsed_plan_id

    @last_parsed_plan_id.setter
    def last_parsed_plan_id(self, value: str | None) -> None:
        with self.lock:
            self._last_parsed_plan_id = value

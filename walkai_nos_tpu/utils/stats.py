"""Shared benchmark statistics helpers.

One percentile definition for every benchmark surface (bench.py,
bench_lm.py): nearest-rank on a pre-sorted sample (rank
ceil(q/100 * n), 1-based). Three diverging inline implementations
(floor-rank vs ceil-rank, fractional vs percent q, 0.0 vs None on
empty) previously made same-named metrics incomparable at small n.
"""

from __future__ import annotations


def percentile(sorted_vals, q_pct: float):
    """Nearest-rank percentile of a pre-sorted sequence; None if empty.

    `q_pct` is in percent (50 = median, 99 = p99).
    """
    if not sorted_vals:
        return None
    n = len(sorted_vals)
    rank = -(-int(q_pct * n) // 100)  # ceil(q/100 * n), 1-based
    return sorted_vals[min(n, max(1, rank)) - 1]


def percentile_interp(sorted_vals, q_pct: float):
    """Linearly interpolated percentile; None if empty.

    For ESTIMATION (e.g. a per-repeat tail statistic feeding a
    confidence interval): nearest-rank jumps between adjacent order
    statistics — on a tunneled runtime those are quantized in whole
    fence RTTs (~0.1 s), which inflates the between-repeat variance
    with pure rank noise. Interpolating between the bracketing order
    statistics is the standard lower-variance estimator. Reported
    headline percentiles stay nearest-rank (a value that actually
    occurred)."""
    if not sorted_vals:
        return None
    n = len(sorted_vals)
    if n == 1:
        return sorted_vals[0]
    pos = (q_pct / 100.0) * (n - 1)
    lo = int(pos)
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac

"""Shared benchmark statistics helpers.

One percentile definition for every benchmark surface (bench.py,
bench_lm.py): nearest-rank on a pre-sorted sample (rank
ceil(q/100 * n), 1-based). Three diverging inline implementations
(floor-rank vs ceil-rank, fractional vs percent q, 0.0 vs None on
empty) previously made same-named metrics incomparable at small n.
"""

from __future__ import annotations


def percentile(sorted_vals, q_pct: float):
    """Nearest-rank percentile of a pre-sorted sequence; None if empty.

    `q_pct` is in percent (50 = median, 99 = p99).
    """
    if not sorted_vals:
        return None
    n = len(sorted_vals)
    rank = -(-int(q_pct * n) // 100)  # ceil(q/100 * n), 1-based
    return sorted_vals[min(n, max(1, rank)) - 1]

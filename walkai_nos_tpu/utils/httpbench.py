"""Shared helpers for driving the demo inference server over HTTP.

Used by the headline bench (`bench.py`) and the serving-path tests
(`tests/test_demo_server.py`) so the boot/teardown and client paths
cannot drift apart.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
SERVER_PATH = os.path.join(
    REPO, "demos", "tpu-sharing-comparison", "app", "main.py"
)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def get_json(url: str, timeout: float = 10.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


def post_json(url: str, payload: dict, timeout: float = 150.0) -> dict:
    """POST a JSON payload, return the decoded JSON response — the
    one definition of the bench client's request path (the serving
    benches all drive `/generate` through this)."""
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def post_infer(base: str, batch: int, timeout: float = 150.0) -> dict:
    return post_json(f"{base}/infer", {"batch": batch}, timeout=timeout)


class InferClient:
    """A persistent-connection client for one bench stream thread.

    urllib opens a new TCP connection per request; at ~100 concurrent
    pipelined streams the handshake + per-connection server thread
    churn becomes the bottleneck being measured. One keep-alive
    connection per stream matches how a real async client drives a
    server. Not thread-safe — one instance per thread."""

    def __init__(self, base: str, timeout: float = 150.0) -> None:
        import http.client
        from urllib.parse import urlparse

        self._netloc = urlparse(base).netloc
        self._timeout = timeout
        self._http = http.client
        self._conn = None

    def post_infer(self, batch: int) -> dict:
        body = json.dumps({"batch": batch})
        headers = {"Content-Type": "application/json"}
        if self._conn is None:
            self._conn = self._http.HTTPConnection(
                self._netloc, timeout=self._timeout
            )
        try:
            self._conn.request("POST", "/infer", body, headers)
            resp = self._conn.getresponse()
            data = resp.read()
        except Exception:
            # Dead keep-alive (server restart, timeout): drop and let
            # the caller retry on a fresh connection.
            self.close()
            raise
        if resp.status != 200:
            # Error responses (send_error) close the server side; keep
            # the client symmetric so the next request reconnects
            # instead of failing once more on a dead socket.
            self.close()
            raise RuntimeError(f"/infer -> {resp.status}")
        return json.loads(data)

    def close(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            finally:
                self._conn = None


def spawn_server(
    env_overrides: dict[str, str],
    startup_timeout_s: float,
    poll_s: float = 0.5,
) -> tuple[subprocess.Popen, str]:
    """Start the demo server on a free port; wait for /healthz.

    Returns (process, base_url); raises RuntimeError (with the process
    reaped) if it exits or never becomes healthy.
    """
    port = free_port()
    env = dict(os.environ)
    env.update(env_overrides)
    env["PORT"] = str(port)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, SERVER_PATH],
        cwd=REPO,
        env=env,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    base = f"http://127.0.0.1:{port}"
    deadline = time.monotonic() + startup_timeout_s
    while True:
        if proc.poll() is not None:
            raise RuntimeError("demo server exited during startup")
        try:
            get_json(f"{base}/healthz", timeout=2.0)
            return proc, base
        except Exception:
            if time.monotonic() > deadline:
                kill_server(proc)
                raise RuntimeError("demo server never became healthy")
            time.sleep(poll_s)


def kill_server(proc: subprocess.Popen) -> None:
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()

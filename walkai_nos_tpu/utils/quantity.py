"""Kubernetes resource.Quantity parsing (integer subset).

Device-plugin (extended) resources are integer quantities, but the k8s API
accepts any Quantity serialization for them ("2", "2k", "2Ki"). The
reference gets this for free from apimachinery; here we implement the
integer subset so controllers never crash on a legally-encoded pod spec.
"""

from __future__ import annotations

from decimal import Decimal, InvalidOperation

_SUFFIXES = {
    "": 1,
    "k": 10**3,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
    "P": 10**15,
    "E": 10**18,
    "Ki": 2**10,
    "Mi": 2**20,
    "Gi": 2**30,
    "Ti": 2**40,
    "Pi": 2**50,
    "Ei": 2**60,
    "m": Decimal("0.001"),
}


def parse_quantity(value: str | int | float) -> int:
    """Parse a k8s Quantity into an integer count.

    Raises ValueError for malformed input or non-integer results (extended
    resources must be whole numbers).
    """
    if isinstance(value, int):
        return value
    if isinstance(value, float):
        if value != int(value):
            raise ValueError(f"quantity {value!r} is not an integer")
        return int(value)
    s = str(value).strip()
    if not s:
        raise ValueError("empty quantity")
    suffix = ""
    for suf in sorted(_SUFFIXES, key=len, reverse=True):
        if suf and s.endswith(suf):
            suffix = suf
            s = s[: -len(suf)]
            break
    try:
        num = Decimal(s)
    except InvalidOperation as e:
        raise ValueError(f"invalid quantity {value!r}") from e
    result = num * Decimal(_SUFFIXES[suffix])
    if result != result.to_integral_value():
        raise ValueError(f"quantity {value!r} is not an integer")
    return int(result)

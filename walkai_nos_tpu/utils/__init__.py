from walkai_nos_tpu.utils.quantity import parse_quantity  # noqa: F401

"""FLOP accounting helpers for utilization/MFU reporting.

The serving bench reports model FLOPs utilization (achieved FLOP/s over
the chip's peak); peaks are the published bf16 dense numbers per TPU
generation. Unknown device kinds return None — the caller reports MFU as
unavailable rather than guessing.
"""

from __future__ import annotations

# Published peak dense bf16 FLOP/s per chip, by `device_kind` substring.
# Checked in order, so more specific strings come first.
_PEAK_BF16_FLOPS: tuple[tuple[str, float], ...] = (
    ("v6 lite", 918e12),  # v6e (Trillium)
    ("v6e", 918e12),
    ("v5 lite", 197e12),  # v5e
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def peak_bf16_flops(device_kind: str) -> float | None:
    """Peak dense bf16 FLOP/s for a jax `device_kind` string, else None."""
    kind = device_kind.lower()
    for marker, peak in _PEAK_BF16_FLOPS:
        if marker in kind:
            return peak
    return None


def vit_flops_per_image(cfg) -> float:
    """Analytic forward-pass FLOPs per image for a ViTConfig.

    Fallback when XLA cost analysis is unavailable: dense matmul FLOPs
    (2mnk) for patch embedding, attention (qkv/out projections + the two
    T^2 contractions), and the MLP, plus the detection heads.
    """
    t = cfg.num_patches + cfg.num_det_tokens
    d = cfg.hidden_dim
    layers = cfg.num_layers
    patch_in = cfg.patch_size * cfg.patch_size * 3
    embed = 2 * cfg.num_patches * patch_in * d
    qkv = 2 * t * d * 3 * d
    attn = 2 * (2 * t * t * d)  # scores + weighted values
    out = 2 * t * d * d
    mlp = 2 * (2 * t * d * cfg.mlp_ratio * d)
    heads = 2 * cfg.num_det_tokens * d * (cfg.num_classes + 4)
    return float(embed + layers * (qkv + attn + out + mlp) + heads)

"""FLOP accounting helpers for utilization/MFU reporting.

The serving bench reports model FLOPs utilization (achieved FLOP/s over
the chip's peak); peaks are the published bf16 dense numbers per TPU
generation. Unknown device kinds return None — the caller reports MFU as
unavailable rather than guessing.
"""

from __future__ import annotations

# Published peak dense bf16 FLOP/s per chip, by `device_kind` substring.
# Checked in order, so more specific strings come first.
_PEAK_BF16_FLOPS: tuple[tuple[str, float], ...] = (
    ("v6 lite", 918e12),  # v6e (Trillium)
    ("v6e", 918e12),
    ("v5 lite", 197e12),  # v5e
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


# Published HBM bandwidth (bytes/s) per chip, same matching rules.
_HBM_BYTES_PER_S: tuple[tuple[str, float], ...] = (
    ("v6 lite", 1640e9),  # v6e (Trillium)
    ("v6e", 1640e9),
    ("v5 lite", 819e9),  # v5e
    ("v5e", 819e9),
    ("v5p", 2765e9),
    ("v5", 2765e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
)


def peak_bf16_flops(device_kind: str) -> float | None:
    """Peak dense bf16 FLOP/s for a jax `device_kind` string, else None."""
    kind = device_kind.lower()
    for marker, peak in _PEAK_BF16_FLOPS:
        if marker in kind:
            return peak
    return None


def hbm_bytes_per_s(device_kind: str) -> float | None:
    """Published HBM bandwidth for a jax `device_kind` string, else None."""
    kind = device_kind.lower()
    for marker, bw in _HBM_BYTES_PER_S:
        if marker in kind:
            return bw
    return None


def roofline(
    flops_per_item: float,
    bytes_per_item: float,
    device_kind: str,
) -> dict | None:
    """Roofline characterization of one model pass on one chip.

    arithmetic_intensity (FLOPs/byte) against the chip's ridge point
    (peak / HBM bandwidth) says WHICH wall bounds the pass:
    below the ridge the attainable rate is bandwidth * intensity
    (memory-bound); above it, the bf16 peak (compute-bound — any
    remaining MFU gap is then occupancy/shape-bound, not a memory wall).
    Returns None when the device kind or byte count is unknown.
    """
    peak = peak_bf16_flops(device_kind)
    bw = hbm_bytes_per_s(device_kind)
    if peak is None or bw is None or bytes_per_item <= 0:
        return None
    intensity = flops_per_item / bytes_per_item
    ridge = peak / bw
    attainable = min(peak, bw * intensity)
    return {
        "arithmetic_intensity_flops_per_byte": round(intensity, 2),
        "ridge_flops_per_byte": round(ridge, 2),
        "bound": "memory" if intensity < ridge else "compute",
        "attainable_flops_per_s": attainable,
        "roofline_mfu_ceiling_pct": round(100.0 * attainable / peak, 2),
    }


def vit_flops_per_image(cfg) -> float:
    """Analytic forward-pass FLOPs per image for a ViTConfig.

    Fallback when XLA cost analysis is unavailable: dense matmul FLOPs
    (2mnk) for patch embedding, attention (qkv/out projections + the two
    T^2 contractions), and the MLP, plus the detection heads.
    """
    t = cfg.num_patches + cfg.num_det_tokens
    d = cfg.hidden_dim
    layers = cfg.num_layers
    patch_in = cfg.patch_size * cfg.patch_size * 3
    embed = 2 * cfg.num_patches * patch_in * d
    qkv = 2 * t * d * 3 * d
    attn = 2 * (2 * t * t * d)  # scores + weighted values
    out = 2 * t * d * d
    mlp = 2 * (2 * t * d * cfg.mlp_ratio * d)
    heads = 2 * cfg.num_det_tokens * d * (cfg.num_classes + 4)
    return float(embed + layers * (qkv + attn + out + mlp) + heads)

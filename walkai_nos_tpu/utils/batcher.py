"""Generic batching with timeout + idle windows.

Port of `pkg/util/batcher.go:25-130` (orphaned in the reference fork —
upstream used it to batch pending pods before planning; kept here for the
same optional use). Semantics: the first item opens a batch and starts the
*timeout* window; each item restarts the *idle* window; the batch is
emitted when either window elapses, and an empty idle-window fire emits
nothing.
"""

from __future__ import annotations

import queue
import threading
from typing import Generic, TypeVar

T = TypeVar("T")


class Batcher(Generic[T]):
    def __init__(
        self, timeout: float, idle: float, buffer_size: int = 0
    ) -> None:
        if timeout <= 0 or idle <= 0:
            raise ValueError("timeout and idle must be > 0")
        self._timeout = timeout
        self._idle = idle
        self._trigger: "queue.Queue[T]" = queue.Queue(maxsize=buffer_size)
        # Unbounded: a bounded output queue would wedge the worker inside
        # a blocking put when the consumer lags, making stop() time out.
        self._batches: "queue.Queue[list[T]]" = queue.Queue()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------------ input

    def add(self, item: T, timeout: float | None = None) -> None:
        """Blocks while a bounded trigger buffer is full (unbuffered
        Batcher = rendezvous, like the reference's unbuffered channel)."""
        self._trigger.put(item, timeout=timeout)

    # ----------------------------------------------------------------- output

    def get_batch(self, timeout: float | None = None) -> list[T]:
        """Next non-empty batch; raises queue.Empty on timeout."""
        return self._batches.get(timeout=timeout)

    # -------------------------------------------------------------- lifecycle

    def _run(self) -> None:
        batch: list[T] = []
        deadline: float | None = None  # timeout-window end
        import time

        while not self._stop.is_set():
            if not batch:
                # Wait for the first item; it opens both windows.
                try:
                    batch.append(self._trigger.get(timeout=0.1))
                except queue.Empty:
                    continue
                deadline = time.monotonic() + self._timeout
                continue
            now = time.monotonic()
            wait = min(self._idle, max(deadline - now, 0.0))
            try:
                batch.append(self._trigger.get(timeout=wait))
                # Idle window restarts on every item; timeout window doesn't.
                if time.monotonic() >= deadline:
                    self._emit(batch)
                    batch, deadline = [], None
            except queue.Empty:
                self._emit(batch)
                batch, deadline = [], None
        if batch:
            self._emit(batch)

    def _emit(self, batch: list[T]) -> None:
        if batch:
            self._batches.put(list(batch))

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="batcher"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)

from walkai_nos_tpu.api.constants import *  # noqa: F401,F403

"""API contract: annotations, labels, resource names.

TPU-native analogue of the reference's contract layer
(`pkg/api/nos.nebuly.com/v1alpha1/annotations.go:22-58`, `labels.go:20-22`,
`constants.go:24-27`, and `pkg/constant/constants.go`). The spec/status
node-annotation protocol is kept structurally identical — it is the
coordination bus between the cluster-scope partitioner and the per-node
agents — with TPU slice shapes in place of MIG profiles.
"""

from __future__ import annotations

# ---------------------------------------------------------------------------
# API group
# ---------------------------------------------------------------------------

API_GROUP = "nos.walkai.io"

# ---------------------------------------------------------------------------
# Node annotations (the control bus).
#
# Spec (desired state, written by the cluster partitioner):
#   nos.walkai.io/spec-tpu-<meshIndex>-<profile>: "<quantity>"
#   nos.walkai.io/spec-partitioning-plan: "<planID>"
# Status (observed state, written by the node agent):
#   nos.walkai.io/status-tpu-<meshIndex>-<profile>-<free|used>: "<quantity>"
#   nos.walkai.io/status-partitioning-plan: "<planID>"
#
# Reference: `pkg/api/nos.nebuly.com/v1alpha1/annotations.go:22-58`.
# ---------------------------------------------------------------------------

ANNOTATION_PARTITIONING_PLAN = f"{API_GROUP}/spec-partitioning-plan"
ANNOTATION_REPORTED_PARTITIONING_PLAN = f"{API_GROUP}/status-partitioning-plan"

ANNOTATION_TPU_SPEC_PREFIX = f"{API_GROUP}/spec-tpu"
ANNOTATION_TPU_STATUS_PREFIX = f"{API_GROUP}/status-tpu"

ANNOTATION_TPU_SPEC_FORMAT = ANNOTATION_TPU_SPEC_PREFIX + "-{index}-{profile}"
ANNOTATION_TPU_STATUS_FORMAT = (
    ANNOTATION_TPU_STATUS_PREFIX + "-{index}-{profile}-{status}"
)

# ---------------------------------------------------------------------------
# Node labels
# ---------------------------------------------------------------------------

# Partitioning-mode node label (reference: `labels.go:20-22`,
# `nos.nebuly.com/gpu-partitioning`). Values: see PartitioningKind.
LABEL_TPU_PARTITIONING = f"{API_GROUP}/tpu-partitioning"

# GKE TPU node labels (the GFD-label analogue; reference consumed
# `nvidia.com/gpu.{product,count,memory}`, `pkg/constant/constants.go:64-77`).
LABEL_TPU_ACCELERATOR = "cloud.google.com/gke-tpu-accelerator"
LABEL_TPU_TOPOLOGY = "cloud.google.com/gke-tpu-topology"
# Multi-host pool membership + the host's position in the pool: every
# node of a GKE multi-host podslice carries the node-pool name and a
# stable worker index — the coordination keys for pool-level planning.
LABEL_TPU_NODEPOOL = "cloud.google.com/gke-nodepool"
LABEL_TPU_WORKER_ID = "cloud.google.com/gke-tpu-worker-id"

# ---------------------------------------------------------------------------
# Resource names
# ---------------------------------------------------------------------------

# Resource prefix for partitioned sub-slices, advertised by the walkai TPU
# device plugin (reference: `nvidia.com/mig-` prefix, constants.go:44-48).
RESOURCE_TPU_SLICE_PREFIX = "walkai.io/tpu-"
# Shared (non-contiguous chip-count) resources — the MPS/slicing analogue.
RESOURCE_TPU_SHARED_PREFIX = "walkai.io/tpu-shared-"
# The native whole-host resource advertised by the stock TPU device plugin.
RESOURCE_TPU = "google.com/tpu"
# Custom scalar resource used by the elastic-quota scheduler (reference:
# `nos.nebuly.com/gpu-memory`, `pkg/api/nos.nebuly.com/v1alpha1/constants.go:24-27`).
RESOURCE_TPU_CHIPS = f"{API_GROUP}/tpu-chips"

# ---------------------------------------------------------------------------
# Controller names (reference: constants.go:25-27)
# ---------------------------------------------------------------------------

PARTITIONER_CONTROLLER_NAME = "tpu-partitioner"
AGENT_REPORTER_NAME = "tpuagent-reporter"
AGENT_ACTUATOR_NAME = "tpuagent-actuator"

# ---------------------------------------------------------------------------
# Environment / defaults (reference: constants.go:58-97)
# ---------------------------------------------------------------------------

ENV_NODE_NAME = "NODE_NAME"

# Device plugin pod selector on TPU-partitioned nodes (reference restarts the
# pod labeled `app=nvidia-device-plugin-daemonset`, `pkg/gpu/client.go:45-49`).
DEVICE_PLUGIN_LABEL_KEY = "app"
DEVICE_PLUGIN_LABEL_VALUE = "walkai-tpu-device-plugin"

DEFAULT_DEVICE_PLUGIN_RESTART_TIMEOUT_S = 60.0
DEFAULT_POD_RESOURCES_TIMEOUT_S = 10.0
DEFAULT_POD_RESOURCES_MAX_MSG_SIZE = 1024 * 1024 * 16
POD_RESOURCES_SOCKET = "/var/lib/kubelet/pod-resources/kubelet.sock"
DEVICE_PLUGIN_SOCKET_DIR = "/var/lib/kubelet/device-plugins"

DEFAULT_AGENT_REPORT_INTERVAL_S = 10.0

"""Spec writer: NodePartitioning -> node annotations.

Port of `internal/partitioning/mig/partitioner.go:40-91`: delete every
existing `spec-tpu-*` annotation, write the new set plus
`spec-partitioning-plan=<planID>`, patch the node (JSON merge patch — the
`client.MergeFrom` analogue).
"""

from __future__ import annotations

import logging

from walkai_nos_tpu.api import constants
from walkai_nos_tpu.kube import objects
from walkai_nos_tpu.kube.client import KubeClient
from walkai_nos_tpu.partitioning.plan_id import new_partitioning_plan_id
from walkai_nos_tpu.partitioning.state import NodePartitioning
from walkai_nos_tpu.tpu.annotations import (
    parse_node_annotations,
    spec_annotations_from_node_partitioning,
)

logger = logging.getLogger(__name__)


class Partitioner:
    def __init__(self, kube: KubeClient):
        self._kube = kube

    def apply_partitioning(
        self,
        node: dict,
        partitioning: NodePartitioning,
        plan_id: str | None = None,
    ) -> str:
        """Write the desired partitioning; returns the plan ID."""
        plan_id = plan_id or new_partitioning_plan_id()
        _, old_spec = parse_node_annotations(objects.annotations(node))
        updates: dict[str, str | None] = {a.key: None for a in old_spec}
        for ann in spec_annotations_from_node_partitioning(
            partitioning.per_mesh_geometry()
        ):
            updates[ann.key] = ann.value
        updates[constants.ANNOTATION_PARTITIONING_PLAN] = plan_id
        self._kube.patch(
            "Node", objects.name(node), objects.annotation_patch(updates)
        )
        logger.info(
            "partitioner: node %s spec updated (plan %s): %s",
            objects.name(node),
            plan_id,
            partitioning.per_mesh_geometry(),
        )
        return plan_id

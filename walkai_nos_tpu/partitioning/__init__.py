"""Cluster-level partitioning: desired state, spec writer, node initializer.

Analogue of `internal/partitioning/{state,mig}/`.
"""

from walkai_nos_tpu.partitioning.state import (  # noqa: F401
    MeshPartitioning,
    NodePartitioning,
    PartitioningState,
    build_node_partitioning,
)
from walkai_nos_tpu.partitioning.partitioner import Partitioner  # noqa: F401
from walkai_nos_tpu.partitioning.initializer import NodeInitializer  # noqa: F401
from walkai_nos_tpu.partitioning.plan_id import new_partitioning_plan_id  # noqa: F401

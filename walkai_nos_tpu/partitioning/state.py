"""Desired-state value types.

Port of `internal/partitioning/state/partitioning.go:24-56` +
`internal/partitioning/mig/state.go:25-45` (node conversion).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from walkai_nos_tpu.tpu.partitioning import Geometry


@dataclass(frozen=True)
class MeshPartitioning:
    """Desired slices for one mesh (`GPUPartitioning` analogue)."""

    mesh_index: int
    resources: tuple[tuple[str, int], ...]  # sorted (profile, qty) pairs

    @staticmethod
    def of(mesh_index: int, geometry: Geometry) -> "MeshPartitioning":
        return MeshPartitioning(
            mesh_index=mesh_index,
            resources=tuple(
                sorted((p, q) for p, q in geometry.items() if q > 0)
            ),
        )

    def geometry(self) -> Geometry:
        return {p: q for p, q in self.resources}


@dataclass(frozen=True)
class NodePartitioning:
    """Desired slices for one node (`NodePartitioning` analogue).

    Equality is order-insensitive by construction (sorted tuples)."""

    name: str
    meshes: tuple[MeshPartitioning, ...] = field(default_factory=tuple)

    def per_mesh_geometry(self) -> dict[int, Geometry]:
        return {m.mesh_index: m.geometry() for m in self.meshes}


class PartitioningState(dict):
    """node name -> NodePartitioning (`PartitioningState` analogue)."""


def build_node_partitioning(node) -> NodePartitioning:
    """tiling.Node -> NodePartitioning (`internal/partitioning/mig/state.go:25-45`)."""
    return NodePartitioning(
        name=node.name,
        meshes=tuple(
            MeshPartitioning.of(idx, geom)
            for idx, geom in sorted(node.geometry().items())
        ),
    )

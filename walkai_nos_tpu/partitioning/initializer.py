"""First-touch node initialization.

Port of `internal/partitioning/mig/initializer.go:40-79`: a freshly labeled
TPU node gets the fewest-slices (coarsest) tiling as its initial spec —
whole-host slices until pending pods ask for something finer.
"""

from __future__ import annotations

import logging

from walkai_nos_tpu.kube import objects
from walkai_nos_tpu.kube.client import KubeClient
from walkai_nos_tpu.partitioning.partitioner import Partitioner
from walkai_nos_tpu.partitioning.state import build_node_partitioning
from walkai_nos_tpu.tpu.tiling.node import Node

logger = logging.getLogger(__name__)


class NodeInitializer:
    def __init__(self, kube: KubeClient, partitioner: Partitioner | None = None):
        self._kube = kube
        self._partitioner = partitioner or Partitioner(kube)

    def init_node_partitioning(self, node_obj: dict) -> None:
        node = Node.from_node(
            objects.name(node_obj),
            objects.labels(node_obj),
            objects.annotations(node_obj),
        )
        if node.model is None:
            logger.warning(
                "initializer: node %s has no recognizable TPU model",
                objects.name(node_obj),
            )
            return
        changed = False
        for mesh in node.meshes:
            if not mesh.geometry():
                if mesh.init_geometry():
                    changed = True
        if not changed:
            return
        self._partitioner.apply_partitioning(
            node_obj, build_node_partitioning(node)
        )

    def init_pool_member(self, node_obj: dict, pool_topo) -> None:
        """First-touch init of one multi-host-pool member: the coarsest
        pool layout is the whole-pool slice, so every member's share is
        the pool profile x1 (the pool analogue of fewest-slices,
        `initializer.go:40-79`). Per-member and idempotent — members
        joining at different times converge to the same spec without
        cross-node coordination."""
        from walkai_nos_tpu.partitioning.state import (
            MeshPartitioning,
            NodePartitioning,
        )

        self._partitioner.apply_partitioning(
            node_obj,
            NodePartitioning(
                name=objects.name(node_obj),
                meshes=(
                    MeshPartitioning.of(0, {pool_topo.pool_profile: 1}),
                ),
            ),
        )

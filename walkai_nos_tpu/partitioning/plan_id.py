"""Plan IDs: UTC unix-nanosecond strings.

Port of `internal/partitioning/mig/plan.go:24-26`. The ID is written with
the spec and echoed back in status so the partitioner can tell which plan a
node's reported state reflects.
"""

from __future__ import annotations

import time
from typing import Callable

# Injectable for tests (the reference injects the generator through
# `InjectFunc`, `mig_controller.go:209-213`).
_now_ns: Callable[[], int] = time.time_ns


def new_partitioning_plan_id() -> str:
    return str(_now_ns())


def set_clock_for_tests(now_ns: Callable[[], int]) -> None:
    global _now_ns
    _now_ns = now_ns

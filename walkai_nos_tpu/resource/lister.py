"""Kubelet pod-resources gRPC client (the real ResourceClient).

Port of `pkg/resource/lister.go:26-38` + `client.go:39-87`: dials the
kubelet's pod-resources unix socket, `List` gives used devices (attached to
pod containers), `GetAllocatableResources` gives everything the kubelet can
allocate; free = allocatable − used is computed by callers
(`pkg/gpu/util.go:62-89`). Same 10s timeout / 16MB max-message defaults
(`pkg/constant/constants.go:89-92`).

gRPC stubs are hand-rolled over grpc.Channel.unary_unary so we don't need
grpc_tools codegen — method paths match the kubelet service
`v1.PodResourcesLister`.
"""

from __future__ import annotations

import grpc

from walkai_nos_tpu.api import constants
from walkai_nos_tpu.protos_gen import podresources_pb2 as pb
from walkai_nos_tpu.resource.client import ResourceClient
from walkai_nos_tpu.tpu.device import Device, DeviceStatus
from walkai_nos_tpu.tpu.errors import GenericError

_SERVICE = "/v1.PodResourcesLister"


class PodResourcesClient(ResourceClient):
    def __init__(
        self,
        socket_path: str = constants.POD_RESOURCES_SOCKET,
        timeout: float = constants.DEFAULT_POD_RESOURCES_TIMEOUT_S,
        max_msg_size: int = constants.DEFAULT_POD_RESOURCES_MAX_MSG_SIZE,
    ) -> None:
        self._timeout = timeout
        self._channel = grpc.insecure_channel(
            f"unix://{socket_path}",
            options=[
                ("grpc.max_receive_message_length", max_msg_size),
                ("grpc.max_send_message_length", max_msg_size),
            ],
        )
        self._list = self._channel.unary_unary(
            f"{_SERVICE}/List",
            request_serializer=pb.ListPodResourcesRequest.SerializeToString,
            response_deserializer=pb.ListPodResourcesResponse.FromString,
        )
        self._allocatable = self._channel.unary_unary(
            f"{_SERVICE}/GetAllocatableResources",
            request_serializer=pb.AllocatableResourcesRequest.SerializeToString,
            response_deserializer=pb.AllocatableResourcesResponse.FromString,
        )

    def close(self) -> None:
        self._channel.close()

    # -------------------------------------------------------------- interface

    def get_allocatable_devices(self, resource_prefix: str = "") -> list[Device]:
        try:
            resp = self._allocatable(
                pb.AllocatableResourcesRequest(), timeout=self._timeout
            )
        except grpc.RpcError as e:
            raise GenericError(f"pod-resources GetAllocatableResources: {e}") from e
        out = []
        for dev in resp.devices:
            if not dev.resource_name.startswith(resource_prefix):
                continue
            for device_id in dev.device_ids:
                out.append(
                    Device(
                        resource_name=dev.resource_name,
                        device_id=device_id,
                        status=DeviceStatus.UNKNOWN,
                    )
                )
        return sorted(out, key=lambda d: d.device_id)

    def get_used_devices(self, resource_prefix: str = "") -> list[Device]:
        try:
            resp = self._list(
                pb.ListPodResourcesRequest(), timeout=self._timeout
            )
        except grpc.RpcError as e:
            raise GenericError(f"pod-resources List: {e}") from e
        out = []
        for pod in resp.pod_resources:
            for container in pod.containers:
                for dev in container.devices:
                    if not dev.resource_name.startswith(resource_prefix):
                        continue
                    for device_id in dev.device_ids:
                        out.append(
                            Device(
                                resource_name=dev.resource_name,
                                device_id=device_id,
                                status=DeviceStatus.USED,
                            )
                        )
        return sorted(out, key=lambda d: d.device_id)

"""Fake kubelet resource client (mock analogue: `pkg/test/mocks/resource/`)."""

from __future__ import annotations

import threading

from walkai_nos_tpu.resource.client import ResourceClient
from walkai_nos_tpu.tpu.device import Device, DeviceStatus


class FakeResourceClient(ResourceClient):
    """In-memory allocatable/used sets keyed by device ID."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._allocatable: dict[str, Device] = {}
        self._used_ids: set[str] = set()

    # ------------------------------------------------------------- test hooks

    def set_allocatable(self, devices: list[Device]) -> None:
        with self._lock:
            self._allocatable = {d.device_id: d for d in devices}

    def mark_used(self, device_id: str) -> None:
        with self._lock:
            self._used_ids.add(device_id)

    def mark_free(self, device_id: str) -> None:
        with self._lock:
            self._used_ids.discard(device_id)

    # -------------------------------------------------------------- interface

    def get_allocatable_devices(self, resource_prefix: str = "") -> list[Device]:
        with self._lock:
            return [
                Device(
                    resource_name=d.resource_name,
                    device_id=d.device_id,
                    status=DeviceStatus.UNKNOWN,
                    mesh_index=d.mesh_index,
                )
                for d in sorted(self._allocatable.values(), key=lambda x: x.device_id)
                if d.resource_name.startswith(resource_prefix)
            ]

    def get_used_devices(self, resource_prefix: str = "") -> list[Device]:
        with self._lock:
            return [
                Device(
                    resource_name=d.resource_name,
                    device_id=d.device_id,
                    status=DeviceStatus.USED,
                    mesh_index=d.mesh_index,
                )
                for d in sorted(self._allocatable.values(), key=lambda x: x.device_id)
                if d.device_id in self._used_ids
                and d.resource_name.startswith(resource_prefix)
            ]

from walkai_nos_tpu.resource.client import ResourceClient  # noqa: F401
from walkai_nos_tpu.resource.fake import FakeResourceClient  # noqa: F401

"""Fake kubelet gRPC services for tests.

Serves the two kubelet boundaries this framework touches, wire-compatible
with the real APIs, over unix sockets in a temp dir: the pod-resources
lister (fed from an in-memory inventory) and the device-plugin Registration
endpoint (records registrations). The gRPC analogue of the reference's
envtest strategy — real protocol, no hardware or kubelet (SURVEY.md §4).
"""

from __future__ import annotations

import os
import threading
from concurrent import futures
from dataclasses import dataclass, field

import grpc

from walkai_nos_tpu.protos_gen import deviceplugin_pb2 as dp
from walkai_nos_tpu.protos_gen import podresources_pb2 as pr


@dataclass
class PodDevices:
    pod_name: str
    namespace: str
    container: str
    resource_name: str
    device_ids: list[str] = field(default_factory=list)


class FakeKubelet:
    def __init__(self, root_dir: str) -> None:
        self.root = root_dir
        os.makedirs(root_dir, exist_ok=True)
        self.pod_resources_socket = os.path.join(root_dir, "kubelet-podres.sock")
        self.plugin_dir = os.path.join(root_dir, "device-plugins")
        os.makedirs(self.plugin_dir, exist_ok=True)
        self.registration_socket = os.path.join(self.plugin_dir, "kubelet.sock")

        self._lock = threading.Lock()
        self._allocatable: list[tuple[str, str]] = []  # (resource, device_id)
        self._used: list[PodDevices] = []
        self.registrations: list[dp.RegisterRequest] = []
        self._servers: list[grpc.Server] = []

    # ------------------------------------------------------------ test hooks

    def set_allocatable(self, devices: list[tuple[str, str]]) -> None:
        with self._lock:
            self._allocatable = list(devices)

    def set_used(self, used: list[PodDevices]) -> None:
        with self._lock:
            self._used = list(used)

    # --------------------------------------------------------------- serving

    def _list(self, request, context):
        with self._lock:
            pods: dict[tuple[str, str], dict[str, list[PodDevices]]] = {}
            for u in self._used:
                pods.setdefault((u.pod_name, u.namespace), {}).setdefault(
                    u.container, []
                ).append(u)
        return pr.ListPodResourcesResponse(
            pod_resources=[
                pr.PodResources(
                    name=name,
                    namespace=ns,
                    containers=[
                        pr.ContainerResources(
                            name=cname,
                            devices=[
                                pr.ContainerDevices(
                                    resource_name=u.resource_name,
                                    device_ids=u.device_ids,
                                )
                                for u in entries
                            ],
                        )
                        for cname, entries in containers.items()
                    ],
                )
                for (name, ns), containers in pods.items()
            ]
        )

    def _get_allocatable(self, request, context):
        with self._lock:
            by_resource: dict[str, list[str]] = {}
            for resource, device_id in self._allocatable:
                by_resource.setdefault(resource, []).append(device_id)
        return pr.AllocatableResourcesResponse(
            devices=[
                pr.ContainerDevices(resource_name=res, device_ids=ids)
                for res, ids in sorted(by_resource.items())
            ]
        )

    def _register(self, request, context):
        with self._lock:
            self.registrations.append(request)
        return dp.Empty()

    def start(self) -> None:
        podres = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
        podres.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    "v1.PodResourcesLister",
                    {
                        "List": grpc.unary_unary_rpc_method_handler(
                            self._list,
                            request_deserializer=pr.ListPodResourcesRequest.FromString,
                            response_serializer=(
                                pr.ListPodResourcesResponse.SerializeToString
                            ),
                        ),
                        "GetAllocatableResources": grpc.unary_unary_rpc_method_handler(
                            self._get_allocatable,
                            request_deserializer=(
                                pr.AllocatableResourcesRequest.FromString
                            ),
                            response_serializer=(
                                pr.AllocatableResourcesResponse.SerializeToString
                            ),
                        ),
                    },
                ),
            )
        )
        podres.add_insecure_port(f"unix://{self.pod_resources_socket}")
        podres.start()
        self._servers.append(podres)

        reg = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
        reg.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    "v1beta1.Registration",
                    {
                        "Register": grpc.unary_unary_rpc_method_handler(
                            self._register,
                            request_deserializer=dp.RegisterRequest.FromString,
                            response_serializer=dp.Empty.SerializeToString,
                        ),
                    },
                ),
            )
        )
        reg.add_insecure_port(f"unix://{self.registration_socket}")
        reg.start()
        self._servers.append(reg)

    def stop(self) -> None:
        for s in self._servers:
            s.stop(grace=0.2)
        self._servers.clear()

"""Kubelet resource introspection (L0').

Analogue of `pkg/resource/client.go:26-29`: ground truth for which
device-plugin devices exist on this node (allocatable) and which are
attached to running containers (used), from the kubelet pod-resources API
(`unix:///var/lib/kubelet/pod-resources/kubelet.sock`). Works identically
for `walkai.io/tpu-*` devices — device plugins are resource-agnostic.
"""

from __future__ import annotations

import abc

from walkai_nos_tpu.tpu.device import Device


class ResourceClient(abc.ABC):
    @abc.abstractmethod
    def get_allocatable_devices(self, resource_prefix: str = "") -> list[Device]:
        """Every device the kubelet can allocate (status unset/unknown).
        Reference: `GetAllocatableDevices` (`pkg/resource/client.go:39-60`)."""

    @abc.abstractmethod
    def get_used_devices(self, resource_prefix: str = "") -> list[Device]:
        """Devices currently attached to pods (status=used).
        Reference: `GetUsedDevices` (`pkg/resource/client.go:62-87`)."""

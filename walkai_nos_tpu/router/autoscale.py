"""Slice autoscaling for the fleet router: reconciler + providers.

The reconciler closes ROADMAP item 4's loop: the serving engines
already export the exact scale signals an autoscaler needs
(`cb_saturation`, windowed `slo_ok`, queue depth — PR 7's SLO layer),
and the partitioner control plane already knows how to carve a TPU
slice on demand (`partitioning/partitioner.py`); this module is the
piece in between. Each `tick()`:

1. **completes drains** — a draining replica whose `has_work` went
   False is retired from the fleet and its slice returned to the
   provider (records were already collected by the router's step
   loop, so retirement drops zero requests);
2. **reads fleet pressure** — a tick is *pressured* when any active
   replica's windowed SLO is measurably breached (`slo_ok is False`,
   i.e. p99 TTFT over its objective) or the mean load (saturation,
   with a queue-based fallback before the first dispatch) crosses
   `up_saturation`; it is *idle* when every load sits under
   `down_saturation` with empty queues;
3. **applies hysteresis + cooldown** — pressure must hold for
   `breach_ticks` CONSECUTIVE ticks before a scale-up, idleness for
   `idle_ticks` before a scale-down, and any scale event opens a
   `cooldown_ticks` window during which no further event fires — so
   a flapping load (breach, recover, breach again inside the window)
   produces exactly one scale-up and one scale-down instead of
   thrashing partitioner plans.

Between hysteresis and the scale steps sits **anomaly evacuation**:
when the fleet's AnomalyDetector flags a replica
(`fleet.anomaly_flagged_names()`), the reconciler auto-triggers its
migrate-first drain (`fleet.start_drain` — resident KV ships to
healthy peers before retirement) without waiting for an idle window,
gated only by the cooldown, `min_replicas`, and any drain already in
flight; the trace event carries `reason="anomaly"`.

Scale-up asks the provider for a slice-backed replica and admits it
to the fleet (power-of-two-choices routing favors it immediately —
it is the least-loaded candidate). Scale-down picks the
least-loaded active replica and calls its `drain()` (the engine
seam: new submits reject, resident slots finish); the router stops
routing to it the same tick, and step 1 retires it once empty.

Providers:

- **`StaticSliceProvider`** — hands out pre-built replicas from a
  fixed pool (CI, the traffic-replay harness, single-host demos).
- **`PartitionerSliceProvider`** — the control-plane path: each
  acquire adds one slice profile to a labeled node's desired
  partitioning and writes it through `Partitioner.apply_partitioning`
  (spec-tpu-* annotations + a fresh plan id — the identical write the
  k8s pod controller performs, which the node's tpuagent actuates
  and its device plugin advertises), then builds the serving replica
  for that slice via the injected `engine_factory`. Release removes
  the slice from the plan and re-applies. Capacity is the node's ICI
  mesh chip count (from its topology label) divided by the profile's
  chips.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass

logger = logging.getLogger(__name__)

__all__ = [
    "PartitionerSliceProvider",
    "Reconciler",
    "ScalePolicy",
    "StaticSliceProvider",
    "replica_load",
]


def replica_load(replica) -> float:
    """Normalized [0, 1] load of one replica: the engine's composed
    saturation when it has refreshed, else a queue-pressure fallback
    (the same queue/(2*slots) normalization the saturation signal
    itself uses) so a replica that never dispatched reads as idle,
    not unknown. A replica whose health probe FAILED (HttpReplica
    `unreachable`) reads as maximum load: its empty signals would
    otherwise score a dead pod 0.0 — the fleet's most attractive
    routing target."""
    if getattr(replica, "unreachable", False):
        return 1.0
    sat = replica.saturation
    if sat is not None:
        return float(sat)
    slots = max(1, getattr(replica, "slots", 1))
    return min(1.0, replica.queue_depth / (2.0 * slots))


@dataclass
class ScalePolicy:
    """Thresholds + hysteresis for the reconciler, in reconcile ticks
    (one tick per router step): deliberately unitless so the same
    policy drives a real-time serving loop and a deterministic
    scripted test."""

    min_replicas: int = 1
    max_replicas: int = 8
    up_saturation: float = 0.85   # mean active load triggering pressure
    down_saturation: float = 0.30  # max active load counting as idle
    breach_ticks: int = 3          # consecutive pressured ticks -> up
    idle_ticks: int = 8            # consecutive idle ticks -> down
    cooldown_ticks: int = 20       # no further event inside this window


class Reconciler:
    """The scale state machine. `tick(fleet)` is called once per
    router step with the fleet facade (`FleetRouter` or any object
    exposing `active_handles()` / `draining_handles()` /
    `add_replica()` / `retire()`); all state lives here, so a
    scripted saturation trace through fake replicas exercises the
    hysteresis exactly as production load does."""

    def __init__(self, provider, policy: ScalePolicy | None = None,
                 obs=None, trace=None):
        self._provider = provider
        self.policy = policy or ScalePolicy()
        self._obs = obs
        # Router trace ring (obs/trace.RouterTrace): every scale
        # action lands there as a structured event with its reason
        # and a signal snapshot, so the fleet /debug/trace shows
        # autoscaler decisions on the same timeline as the traffic
        # that caused them (scale counters alone say WHAT happened,
        # never WHY or WHEN relative to the surge).
        self._trace = trace
        self._tick = 0
        self._over = 0
        self._under = 0
        self._cooldown_until = 0

    def _trace_event(self, name: str, **args) -> None:
        if self._trace is not None:
            self._trace.event(name, time.monotonic(), **args)

    @staticmethod
    def _signal_snapshot(active) -> dict:
        """The evidence a scale decision was made on, JSON-shaped for
        the trace event: per-replica load and SLO bit plus total
        queue depth at the decision tick."""
        return {
            "loads": {
                h.name: round(replica_load(h.replica), 4)
                for h in active
            },
            "slo_ok": {h.name: h.replica.slo_ok for h in active},
            "queue": sum(h.replica.queue_depth for h in active),
        }

    # -- signals -------------------------------------------------------

    def _pressured(self, active) -> bool:
        if not active:
            return True  # traffic with zero active replicas IS pressure
        if any(h.replica.slo_ok is False for h in active):
            return True
        loads = [replica_load(h.replica) for h in active]
        return sum(loads) / len(loads) >= self.policy.up_saturation

    def _idle(self, active) -> bool:
        if not active:
            return False
        return all(
            replica_load(h.replica) <= self.policy.down_saturation
            and h.replica.queue_depth == 0
            for h in active
        )

    def _event(self, direction: str) -> None:
        self._cooldown_until = self._tick + self.policy.cooldown_ticks
        self._over = 0
        self._under = 0
        if self._obs is not None:
            self._obs.scale_events.inc(
                labels={"direction": direction}
            )

    # -- the loop ------------------------------------------------------

    def tick(self, fleet) -> None:
        self._tick += 1
        # 1. Complete drains: retirement drops nothing — the router's
        # step loop already collected every record, and has_work False
        # means queue, lanes, slots, and in-flight chunks are all
        # empty.
        for handle in list(fleet.draining_handles()):
            if not handle.replica.has_work:
                fleet.retire(handle)
                self._provider.release(handle.replica)
                self._trace_event(
                    "release", replica=handle.name,
                    reason="drained",
                    signals=self._signal_snapshot(
                        fleet.active_handles()
                    ),
                )
                logger.info(
                    "router: replica %s drained and released",
                    handle.name,
                )
        active = fleet.active_handles()
        flagged_names: set[str] = set()
        flagged_of = getattr(fleet, "anomaly_flagged_names", None)
        if flagged_of is not None:
            flagged_names = set(flagged_of())
        # 2. Consecutive-tick hysteresis counters.
        pressured = self._pressured(active)
        self._over = self._over + 1 if pressured else 0
        self._under = self._under + 1 if self._idle(active) else 0
        if self._tick < self._cooldown_until:
            return
        # 2b. Anomaly evacuation: a replica the fleet's AnomalyDetector
        # flagged is rotated out NOW — `start_drain` is migrate-first
        # (PR 16), so its resident KV ships to healthy peers instead of
        # finishing on the sick chip. No hysteresis (the detector's own
        # window IS the debounce), but the cooldown gate above still
        # rate-limits to one evacuation per window, min_replicas is
        # respected, and an in-flight drain defers the next victim.
        if (
            flagged_names
            and len(active) > self.policy.min_replicas
            and not fleet.draining_handles()
        ):
            flagged = [h for h in active if h.name in flagged_names]
            if flagged:
                victim = max(
                    flagged, key=lambda h: replica_load(h.replica)
                )
                fleet.start_drain(victim)
                self._event("down")
                self._trace_event(
                    "drain_start", replica=victim.name,
                    reason="anomaly",
                    signals=self._signal_snapshot(active),
                )
                logger.info(
                    "router: anomaly evacuation draining replica %s",
                    victim.name,
                )
                return
        # 3a. Scale up.
        if (
            self._over >= self.policy.breach_ticks
            and len(active) < self.policy.max_replicas
        ):
            replica = self._provider.acquire()
            if replica is None:
                # No capacity: note it, re-accumulate a full breach
                # window before asking again (a dry provider must not
                # be hammered every tick).
                self._over = 0
                if self._obs is not None:
                    self._obs.scale_events.inc(
                        labels={"direction": "denied"}
                    )
                self._trace_event(
                    "scale_denied",
                    reason="provider_dry",
                    signals=self._signal_snapshot(active),
                )
                return
            fleet.add_replica(replica)
            self._event("up")
            self._trace_event(
                "scale_up", replica=replica.name,
                reason=(
                    "slo_breach"
                    if any(h.replica.slo_ok is False for h in active)
                    else "saturation"
                ),
                signals=self._signal_snapshot(active),
            )
            logger.info(
                "router: scale-up admitted replica %s", replica.name
            )
            return
        # 3b. Scale down: drain a flagged straggler if the fleet's
        # anomaly detector singled one out (the drain hint — an idle
        # window is exactly when rotating a sick replica out is
        # free), else the least-loaded active replica.
        if (
            self._under >= self.policy.idle_ticks
            and len(active) > self.policy.min_replicas
        ):
            pool = [
                h for h in active if h.name in flagged_names
            ] or active
            victim = min(
                pool, key=lambda h: replica_load(h.replica)
            )
            fleet.start_drain(victim)
            self._event("down")
            self._trace_event(
                "drain_start", replica=victim.name,
                reason=(
                    "anomaly" if victim.name in flagged_names
                    else "idle"
                ),
                signals=self._signal_snapshot(active),
            )
            logger.info(
                "router: scale-down draining replica %s", victim.name
            )


class StaticSliceProvider:
    """Pre-built replicas handed out in order — the CI / harness
    provider. Released replicas are NOT recycled (a drained engine's
    drain is one-way); they land in `released` for assertions."""

    def __init__(self, replicas=()):
        self._pool = list(replicas)
        self.released: list = []

    def acquire(self):
        return self._pool.pop(0) if self._pool else None

    def release(self, replica) -> None:
        self.released.append(replica)


class PartitionerSliceProvider:
    """Slices through the partitioner control plane.

    `acquire()` finds a node with free mesh capacity, adds one
    `profile` slice to its desired partitioning, writes the plan with
    `Partitioner.apply_partitioning` (spec annotations + plan id on
    the Node object — the write the tpuagent actuates), and returns
    `engine_factory(slice_name)`. `release()` reverses the geometry
    delta and re-applies. The provider owns exactly ONE spec entry —
    its (mesh_index, profile) pair — and every write MERGES that
    entry into the node's current spec annotations before applying:
    `apply_partitioning` rewrites the whole spec-annotation set, so a
    plan built from the provider's own count alone would wipe
    pod-controller-managed slices (and other meshes' geometry) off
    any node the two writers share.
    """

    def __init__(
        self,
        kube,
        node_names,
        *,
        engine_factory,
        profile: str = "1x1",
        mesh_index: int = 0,
    ):
        from walkai_nos_tpu.partitioning.partitioner import Partitioner
        from walkai_nos_tpu.tpu.tiling.profile import Profile

        self._kube = kube
        self._partitioner = Partitioner(kube)
        self._nodes = list(node_names)
        self._factory = engine_factory
        self.profile = profile
        self._mesh_index = mesh_index
        self._chips = Profile.parse(profile).chips
        self._count: dict[str, int] = {n: 0 for n in self._nodes}
        self._node_of: dict[int, str] = {}  # id(replica) -> node
        self._seq = 0
        self.plan_ids: list[str] = []

    def _capacity(self, node_name: str) -> int:
        from walkai_nos_tpu.api import constants
        from walkai_nos_tpu.kube import objects
        from walkai_nos_tpu.tpu import topology

        node = self._kube.get("Node", node_name)
        label = objects.labels(node).get(
            constants.LABEL_TPU_TOPOLOGY, "2x4"
        )
        return topology.shape_chip_count(
            topology.parse_shape(label)
        ) // self._chips

    def _apply(self, node_name: str) -> str:
        from walkai_nos_tpu.kube import objects
        from walkai_nos_tpu.partitioning.state import (
            MeshPartitioning,
            NodePartitioning,
        )
        from walkai_nos_tpu.tpu.annotations import parse_node_annotations

        node = self._kube.get("Node", node_name)
        # Merge-then-write: the node's current spec annotations are the
        # base plan; only this provider's (mesh, profile) entry is
        # replaced by its tracked count (or dropped at zero). Everything
        # another writer put there rides through the rewrite untouched.
        _, spec = parse_node_annotations(objects.annotations(node))
        per_mesh: dict[int, dict[str, int]] = {}
        for ann in spec:
            per_mesh.setdefault(ann.mesh_index, {})[ann.profile] = (
                ann.quantity
            )
        mesh = per_mesh.setdefault(self._mesh_index, {})
        if self._count[node_name]:
            mesh[self.profile] = self._count[node_name]
        else:
            mesh.pop(self.profile, None)
        plan_id = self._partitioner.apply_partitioning(
            node,
            NodePartitioning(
                name=node_name,
                meshes=tuple(
                    MeshPartitioning.of(idx, geometry)
                    for idx, geometry in sorted(per_mesh.items())
                ),
            ),
        )
        self.plan_ids.append(plan_id)
        return plan_id

    def acquire(self):
        for node_name in self._nodes:
            if self._count[node_name] >= self._capacity(node_name):
                continue
            self._count[node_name] += 1
            self._apply(node_name)
            slice_name = (
                f"{node_name}/{self.profile}-{self._seq}"
            )
            self._seq += 1
            replica = self._factory(slice_name)
            self._node_of[id(replica)] = node_name
            return replica
        return None

    def release(self, replica) -> None:
        node_name = self._node_of.pop(id(replica), None)
        if node_name is None:
            return
        self._count[node_name] = max(0, self._count[node_name] - 1)
        self._apply(node_name)

"""Fleet router + slice autoscaler: multi-engine serving over the
partitioner control plane (ROADMAP item 4).

- `router.core` — `FleetRouter`: prefix-affinity routing (first
  128-token block hashed to the replica whose radix trie holds it —
  `models/block_key.route_key`, the trie's own block identity) with
  a power-of-two-choices load fallback, behind a single-engine-
  shaped `submit()`/`step()`/`drain_done_records()` surface; KV
  block shipping makes the prefix cache fleet-global, and
  `add_replica(role="prefill"|"decode")` turns placement two-stage
  (disaggregated serving with first-token stream handoff and
  migrate-first drain-down — docs/serving-router.md).
- `router.replica` — `EngineReplica` (in-process `ContinuousBatcher`,
  CI and single host) and `HttpReplica` (remote demo-server pod) —
  one interface, two deployment shapes.
- `router.autoscale` — the reconciler (hysteresis + cooldown over
  `cb_saturation`/`slo_ok`/queue depth; drain-then-release
  scale-down) and its slice providers (`StaticSliceProvider`,
  `PartitionerSliceProvider` through
  `partitioning/partitioner.py`).

Front-end binary: `cmd/serverouter.py`. Traffic-replay harness:
`sim/trafficbench.py`. Metrics: the `router_*` series in
`obs/catalog.py` (docs/serving-router.md has the routing policy and
the scale state machine).
"""

from walkai_nos_tpu.router.autoscale import (  # noqa: F401
    PartitionerSliceProvider,
    Reconciler,
    ScalePolicy,
    StaticSliceProvider,
    replica_load,
)
from walkai_nos_tpu.router.core import FleetRouter, prefix_key  # noqa: F401
from walkai_nos_tpu.router.replica import (  # noqa: F401
    EngineReplica,
    HttpReplica,
)

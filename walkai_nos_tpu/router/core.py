"""FleetRouter: prefix-affinity request routing over N engine replicas.

The front-end that finally makes the repo's two halves serve traffic
TOGETHER (ROADMAP item 4): a fleet of `ContinuousBatcher` replicas —
one per TPU slice the partitioner carved — behind one `submit()`/
`step()`/`drain_done_records()` surface shaped exactly like a single
engine's, so every existing driver loop (the demo server's, the
bench's, the traffic harness's) can front a fleet unchanged.

Routing is CACHE-AWARE, LOAD-BOUNDED — the radix-affinity insight of
SGLang-style routers, grounded in this repo's own prefix cache:

- **Prefix-affinity**: the routing key is a hash of the prompt's
  FIRST 128-token block (`PAGE_ROWS` — the radix trie's own block
  granularity: the smallest unit `models/prefix_cache.py` can share).
  Same-template traffic therefore carries the same key, and the
  affinity map steers it to the replica whose trie already holds the
  template's blocks: the fleet-level win is that each template's
  prefix is prefilled ONCE PER FLEET instead of once per replica,
  which is what `router_prefix_hit_rate` (the fleet-aggregated
  `cb_prefix_hit_rate`) measures. Prompts shorter than one block have
  nothing shareable and skip straight to load balancing.
- **Power-of-two-choices fallback**: affinity never overloads a hot
  replica — when the affinity target's load (engine saturation, with
  a queue fallback) is at or past `affinity_overload`, the router
  samples two candidates, takes the less loaded (Mitzenmacher's d=2
  bound: near-best-of-all balance at O(1) probes), and migrates the
  template there ONLY if that destination is at least
  `affinity_imbalance` less loaded than the target (a uniformly
  saturated fleet gains nothing from moving and would pay a cold
  prefill per migration; a sampled pair hotter than the target must
  never inherit the stream). On migration the template's affinity
  RE-POINTS, so the overflow replica warms the template's blocks
  once and inherits the stream. Unknown keys route through the same
  two-choice sample.
- **Draining replicas receive nothing**: the candidate set is the
  non-draining fleet, checked per request — the scale-down
  invariant the reconciler's drain lifecycle relies on.

`policy="round_robin"` disables the affinity map (pure rotation) —
the baseline arm the traffic harness compares the hit rate against.

The router is single-driver-threaded like the engine itself: one
thread calls `submit()`/`step()`; `step()` advances every replica one
turn, ticks the autoscaling reconciler (`router/autoscale.py`), and
collects finished records fleet-wide (records survive replica
retirement — they are pulled every step, BEFORE a drained replica is
released). Scale signals and fleet telemetry flow through
`obs/router.RouterObs` (`router_*` catalog series).
"""

from __future__ import annotations

import random
import zlib

import numpy as np

from walkai_nos_tpu.obs.router import RouterObs
from walkai_nos_tpu.ops.decode_attention import PAGE_ROWS
from walkai_nos_tpu.router.autoscale import Reconciler, replica_load

__all__ = ["FleetRouter", "prefix_key"]


def prefix_key(prompt) -> int | None:
    """Routing key: CRC-32 of the prompt's first full 128-token block
    (PAGE_ROWS — the prefix trie's share granularity), None when the
    prompt has no full block to share. Stable across processes (no
    PYTHONHASHSEED dependence), so a router restart re-derives the
    same template keys."""
    prompt = np.asarray(prompt).reshape(-1)
    if len(prompt) < PAGE_ROWS:
        return None
    return zlib.crc32(
        prompt[:PAGE_ROWS].astype(np.int64).tobytes()
    )


class _Handle:
    """One fleet member: the replica plus the router's bookkeeping
    (request count, the final prefix tallies captured at retirement)."""

    def __init__(self, replica, name: str):
        self.replica = replica
        self.name = name
        self.routed = 0

    def prefix_tallies(self) -> tuple[int, int]:
        stats = self.replica.prefix_stats() or {}
        return (
            int(stats.get("block_hits") or 0),
            int(stats.get("block_hits") or 0)
            + int(stats.get("block_misses") or 0),
        )


class FleetRouter:
    def __init__(
        self,
        replicas=(),
        *,
        provider=None,
        scale_policy=None,
        policy: str = "affinity",
        affinity_overload: float = 0.9,
        affinity_imbalance: float = 0.25,
        seed: int = 0,
        obs: RouterObs | bool = True,
    ):
        if policy not in ("affinity", "round_robin"):
            raise ValueError(
                f"policy must be 'affinity' or 'round_robin'; "
                f"got {policy!r}"
            )
        self.policy = policy
        self.affinity_overload = affinity_overload
        self.affinity_imbalance = affinity_imbalance
        if isinstance(obs, RouterObs):
            self.obs = obs
        else:
            self.obs = RouterObs(enabled=bool(obs))
        self._rng = random.Random(seed)
        self._handles: list[_Handle] = []
        self._seq = 0
        for replica in replicas:
            self.add_replica(replica)
        # template key -> handle (affinity map); entries for retired
        # handles are dropped lazily at lookup.
        self._affinity: dict[int, _Handle] = {}
        self._rr_next = 0
        self._next_rid = 0
        # router rid -> (handle, local rid); completed records land in
        # _done keyed by router rid.
        self._routes: dict[int, tuple[_Handle, int]] = {}
        self._local: dict[tuple[int, int], int] = {}
        self._done: dict[int, dict] = {}
        # Prefix tallies of replicas already retired, so the fleet hit
        # rate never loses history when a slice is returned.
        self._retired_hits = 0
        self._retired_lookups = 0
        self._reconciler = (
            Reconciler(provider, scale_policy, obs=self.obs)
            if provider is not None else None
        )
        self._set_replica_gauges()

    # -- fleet membership ----------------------------------------------

    def add_replica(self, replica) -> None:
        name = getattr(replica, "name", None) or f"r{self._seq}"
        self._seq += 1
        self._handles.append(_Handle(replica, name))
        self._set_replica_gauges()

    def start_drain(self, handle: _Handle) -> None:
        """Stop routing to `handle` and ask its replica to drain
        (resident work finishes; the reconciler retires it once
        `has_work` goes False)."""
        handle.replica.drain()
        self._set_replica_gauges()

    def retire(self, handle: _Handle) -> None:
        """Remove a fully drained handle from the fleet, folding its
        prefix tallies into the retired accumulators first so the
        fleet-level hit rate keeps its history."""
        self._collect(handle)  # final records, before the handle goes
        hits, lookups = handle.prefix_tallies()
        self._retired_hits += hits
        self._retired_lookups += lookups
        self._handles.remove(handle)
        self._affinity = {
            k: h for k, h in self._affinity.items() if h is not handle
        }
        # Drop the retired replica's per-replica series: its last
        # saturation would otherwise export as a live member forever.
        self.obs.replica_saturation.remove(
            labels={"replica": handle.name}
        )
        self._set_replica_gauges()

    def active_handles(self) -> list[_Handle]:
        return [
            h for h in self._handles if not h.replica.draining
        ]

    def draining_handles(self) -> list[_Handle]:
        return [h for h in self._handles if h.replica.draining]

    @property
    def replicas(self) -> list:
        return [h.replica for h in self._handles]

    # -- routing -------------------------------------------------------

    def _pick(self, key: int | None) -> tuple[_Handle, str]:
        candidates = self.active_handles()
        if not candidates:
            self.obs.failed.inc(labels={"reason": "no_replica"})
            raise RuntimeError(
                "fleet has no active replica to route to"
            )
        if self.policy == "round_robin":
            handle = candidates[self._rr_next % len(candidates)]
            self._rr_next += 1
            return handle, "round_robin"
        if key is not None:
            handle = self._affinity.get(key)
            if handle is not None and handle in candidates:
                load = replica_load(handle.replica)
                # Affinity yields only when the target is HOT *and*
                # the sampled alternative is meaningfully less loaded
                # THAN THE TARGET: a uniformly saturated fleet (every
                # engine's busy component pinned at 1.0 under full
                # load) gains nothing from moving and would pay a
                # cold prefill per migration. The gap is checked
                # against the actual migration destination, not the
                # fleet minimum — a lucky global minimum must not
                # green-light re-pointing to whatever two replicas
                # the sample happened to draw (possibly hotter than
                # the target itself).
                if load < self.affinity_overload:
                    return handle, "affinity"
                alt = self._two_choices(candidates)
                if (
                    load - replica_load(alt.replica)
                    >= self.affinity_imbalance
                ):
                    self._affinity[key] = alt
                    return alt, "p2c"
                return handle, "affinity"
        # Unknown key (or no affinity yet): two-choice placement; the
        # key (if any) points here so the template's stream follows
        # the blocks it is about to warm.
        handle = self._two_choices(candidates)
        if key is not None:
            self._affinity[key] = handle
        return handle, "p2c"

    def _two_choices(self, candidates: list[_Handle]) -> _Handle:
        """Power-of-two-choices: two distinct candidates when the
        fleet has them, least loaded wins (Mitzenmacher's d=2 bound:
        near-best-of-all balance at O(1) probes)."""
        if len(candidates) == 1:
            return candidates[0]
        a, b = self._rng.sample(candidates, 2)
        return min((a, b), key=lambda h: replica_load(h.replica))

    def submit(self, prompt, **kwargs) -> int:
        """Route one request; returns a ROUTER request id (replica
        rids are namespaced per replica and never leak). Replica-side
        validation errors (bad knobs, oversize) propagate to the
        caller after landing in `router_requests_failed_total` —
        client errors stay client errors whatever replica they hit."""
        handle, arm = self._pick(prefix_key(prompt))
        try:
            local = handle.replica.submit(prompt, **kwargs)
        except ValueError:
            self.obs.failed.inc(labels={"reason": "bad_request"})
            raise
        rid = self._next_rid
        self._next_rid += 1
        self._routes[rid] = (handle, local)
        self._local[(id(handle), local)] = rid
        handle.routed += 1
        self.obs.submitted.inc()
        self.obs.routed.inc(labels={"policy": arm})
        return rid

    # -- the drive loop ------------------------------------------------

    def _collect(self, handle: _Handle) -> None:
        for local, record in handle.replica.drain_done_records().items():
            rid = self._local.pop((id(handle), local), None)
            if rid is None:
                continue  # a request submitted around the router
            self._routes.pop(rid, None)
            record = dict(record)
            record["replica"] = handle.name
            self._done[rid] = record

    def step(self) -> bool:
        """One fleet turn: advance every replica (draining ones
        included — their resident work is what a drain waits for),
        collect finished records, tick the reconciler, refresh the
        fleet gauges. True while any replica still has work."""
        for handle in list(self._handles):
            handle.replica.step()
            self._collect(handle)
        if self._reconciler is not None:
            self._reconciler.tick(self)
        self._refresh_gauges()
        return self.has_work

    def run(self) -> dict[int, list[int]]:
        """Drive until every routed request finishes."""
        out: dict[int, list[int]] = {}
        while self.has_work:
            self.step()
            out.update(self.drain_done())
        out.update(self.drain_done())
        return out

    @property
    def has_work(self) -> bool:
        return bool(self._routes) or any(
            h.replica.has_work for h in self._handles
        )

    def drain_done_records(self) -> dict[int, dict]:
        done, self._done = self._done, {}
        return done

    def drain_done(self) -> dict[int, list[int]]:
        return {
            rid: rec["tokens"]
            for rid, rec in self.drain_done_records().items()
        }

    # -- telemetry -----------------------------------------------------

    def _set_replica_gauges(self) -> None:
        active = [h for h in self._handles if not h.replica.draining]
        self.obs.replicas_gauge.set(
            len(active), labels={"state": "active"}
        )
        self.obs.replicas_gauge.set(
            len(self._handles) - len(active),
            labels={"state": "draining"},
        )

    def _refresh_gauges(self) -> None:
        self._set_replica_gauges()
        self.obs.queue_depth.set(
            sum(h.replica.queue_depth for h in self._handles)
        )
        for handle in self._handles:
            sat = handle.replica.saturation
            if sat is not None:
                self.obs.replica_saturation.set(
                    sat, labels={"replica": handle.name}
                )
        rate = self.prefix_hit_rate
        if rate is not None:
            self.obs.prefix_hit_rate.set(round(rate, 4))

    @property
    def prefix_hit_rate(self) -> float | None:
        """Fleet-level prefix-cache block hit rate: hits over
        lookupable blocks summed across live AND retired replicas —
        the metric prefix-affinity routing exists to raise."""
        hits, lookups = self._retired_hits, self._retired_lookups
        for handle in self._handles:
            h, lk = handle.prefix_tallies()
            hits += h
            lookups += lk
        return hits / lookups if lookups else None

    def scale_events(self) -> dict[str, int]:
        return {
            d: int(self.obs.scale_events.value(
                labels={"direction": d}
            ))
            for d in ("up", "down", "denied")
        }

    def stats(self) -> dict:
        """One fleet snapshot: membership, per-replica signals and
        routed counts, affinity-map size, fleet prefix hit rate, and
        the scale-event tallies — the serverouter `/healthz` fleet
        block and the traffic harness's read surface."""
        rate = self.prefix_hit_rate
        return {
            **({} if self.obs.enabled else {"obs_disabled": True}),
            "policy": self.policy,
            "replicas": [
                {
                    "name": h.name,
                    "draining": h.replica.draining,
                    "saturation": h.replica.saturation,
                    "slo_ok": h.replica.slo_ok,
                    "queue_depth": h.replica.queue_depth,
                    "has_work": h.replica.has_work,
                    "routed": h.routed,
                }
                for h in self._handles
            ],
            "active": len(self.active_handles()),
            "draining": len(self.draining_handles()),
            "affinity_keys": len(self._affinity),
            "prefix_hit_rate": (
                round(rate, 4) if rate is not None else None
            ),
            "scale_events": self.scale_events(),
            "in_flight": len(self._routes),
        }

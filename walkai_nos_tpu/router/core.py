"""FleetRouter: prefix-affinity request routing over N engine replicas.

The front-end that finally makes the repo's two halves serve traffic
TOGETHER (ROADMAP item 4): a fleet of `ContinuousBatcher` replicas —
one per TPU slice the partitioner carved — behind one `submit()`/
`step()`/`drain_done_records()` surface shaped exactly like a single
engine's, so every existing driver loop (the demo server's, the
bench's, the traffic harness's) can front a fleet unchanged.

Routing is CACHE-AWARE, LOAD-BOUNDED — the radix-affinity insight of
SGLang-style routers, grounded in this repo's own prefix cache:

- **Prefix-affinity**: the routing key is a hash of the prompt's
  FIRST 128-token block (`PAGE_ROWS` — the radix trie's own block
  granularity: the smallest unit `models/prefix_cache.py` can share).
  Same-template traffic therefore carries the same key, and the
  affinity map steers it to the replica whose trie already holds the
  template's blocks: the fleet-level win is that each template's
  prefix is prefilled ONCE PER FLEET instead of once per replica,
  which is what `router_prefix_hit_rate` (the fleet-aggregated
  `cb_prefix_hit_rate`) measures. Prompts shorter than one block have
  nothing shareable and skip straight to load balancing.
- **Power-of-two-choices fallback**: affinity never overloads a hot
  replica — when the affinity target's load (engine saturation, with
  a queue fallback) is at or past `affinity_overload`, the router
  samples two candidates, takes the less loaded (Mitzenmacher's d=2
  bound: near-best-of-all balance at O(1) probes), and migrates the
  template there ONLY if that destination is at least
  `affinity_imbalance` less loaded than the target (a uniformly
  saturated fleet gains nothing from moving and would pay a cold
  prefill per migration; a sampled pair hotter than the target must
  never inherit the stream). On migration the template's affinity
  RE-POINTS, so the overflow replica warms the template's blocks
  once and inherits the stream. Unknown keys route through the same
  two-choice sample.
- **Draining replicas receive nothing**: the candidate set is the
  non-draining fleet, checked per request — the scale-down
  invariant the reconciler's drain lifecycle relies on.

`policy="round_robin"` disables the affinity map (pure rotation) —
the baseline arm the traffic harness compares the hit rate against.

The router is single-driver-threaded like the engine itself: one
thread calls `submit()`/`step()`; `step()` advances every replica one
turn, ticks the autoscaling reconciler (`router/autoscale.py`), and
collects finished records fleet-wide (records survive replica
retirement — they are pulled every step, BEFORE a drained replica is
released). Scale signals and fleet telemetry flow through
`obs/router.RouterObs` (`router_*` catalog series).

The router also carries the FLEET OBSERVABILITY PLANE on the same
driver loop: it mints a trace id per request and records its own
route/queue/round-trip spans (`obs/trace.RouterTrace`; merged with
replica traces by `fleet_trace()`), re-exports every replica's
engine series under a `replica` label (`federated_metrics()`),
scores each replica's windowed signals against the fleet for
straggler detection (`obs/anomaly.py`; the score feeds routing as a
load penalty and the reconciler as a drain hint), and dumps a
flight-recorder bundle on anomaly flips and SLO-breach edges.

The SHADOW/CANARY plane (ROADMAP 4b) rides the same loop:
`add_replica(role="canary")` registers ONE candidate-config replica
that receives a mirrored copy of a sampled fraction of live submits
(`canary_mirror`) — same prompt, knobs, and effective seed (pinned
router-side for unseeded sampled requests, so both streams draw the
same PRNG sequence). The primary's response serves the user; the
mirror is INVISIBLE to every placement and scale decision (excluded
from `active_handles()`, the affinity/block-home maps, queue-depth
and capacity gauges, anomaly peer scoring, and migration targets) but
federates its `cb_*` series and exports `router_canary_*` like any
member. `obs/canary.CanaryController` diffs the paired completions
(digest-exact when the config delta is token-preserving) and holds
the verdict machine; the router applies it each step — promote flips
the canary to a full serving role, reject drains it migrate-first
with trace reason `canary_reject`.
"""

from __future__ import annotations

import random
import time

import numpy as np

from walkai_nos_tpu.models.block_key import chain_hashes, route_key
from walkai_nos_tpu.obs.anomaly import AnomalyDetector, FlightRecorder
from walkai_nos_tpu.obs.canary import CanaryController
from walkai_nos_tpu.obs.capture import (
    CaptureLog,
    fingerprint_id,
    token_digest,
)
from walkai_nos_tpu.obs.federation import federate, merge_fleet_trace
from walkai_nos_tpu.obs.router import RouterObs
from walkai_nos_tpu.obs.trace import RouterTrace
from walkai_nos_tpu.ops.decode_attention import PAGE_ROWS
from walkai_nos_tpu.router.autoscale import Reconciler, replica_load

__all__ = ["FleetRouter", "prefix_key"]


def prefix_key(prompt) -> int | None:
    """Routing key: CRC-32 of the prompt's first full 128-token block
    (PAGE_ROWS — the prefix trie's share granularity), None when the
    prompt has no full block to share. Delegates to
    `models/block_key.route_key` so the router's affinity key and the
    trie's block identity derive from ONE serialization of the same
    tokens (`block_key`) — the key a block ships under is the key
    traffic routes under. Stable across processes (no PYTHONHASHSEED
    dependence), so a router restart re-derives the same template
    keys."""
    return route_key(prompt)


class _Handle:
    """One fleet member: the replica plus the router's bookkeeping
    (request count, the final prefix tallies captured at retirement,
    the fleet plane's per-replica state: last anomaly verdict, scrape
    error counts already reflected into the counter, and the
    SLO-breach edge detector the flight recorder triggers on)."""

    def __init__(self, replica, name: str, role: str = "both"):
        self.replica = replica
        self.name = name
        self.role = role
        self.routed = 0
        self.anomaly: dict = {"score": 0.0, "flagged": False}
        self.scrape_seen: dict[str, int] = {}
        self.slo_was_false = False

    def can_prefill(self) -> bool:
        return self.role in ("both", "prefill")

    def can_decode(self) -> bool:
        return self.role in ("both", "decode")

    def prefix_tallies(self) -> tuple[int, int]:
        stats = self.replica.prefix_stats() or {}
        return (
            int(stats.get("block_hits") or 0),
            int(stats.get("block_hits") or 0)
            + int(stats.get("block_misses") or 0),
        )


class FleetRouter:
    def __init__(
        self,
        replicas=(),
        *,
        provider=None,
        scale_policy=None,
        policy: str = "affinity",
        affinity_overload: float = 0.9,
        affinity_imbalance: float = 0.25,
        ship_blocks: bool = True,
        seed: int = 0,
        obs: RouterObs | bool = True,
        trace: RouterTrace | None = None,
        anomaly: AnomalyDetector | bool | None = None,
        anomaly_penalty: float = 0.5,
        fleet_refresh_s: float = 1.0,
        flight: FlightRecorder | None = None,
        flight_dir: str | None = None,
        capture: CaptureLog | str | None = None,
        canary_mirror: float = 1.0,
        canary_opts: dict | None = None,
    ):
        if policy not in ("affinity", "round_robin"):
            raise ValueError(
                f"policy must be 'affinity' or 'round_robin'; "
                f"got {policy!r}"
            )
        self.policy = policy
        self.affinity_overload = affinity_overload
        self.affinity_imbalance = affinity_imbalance
        # Block shipping on placement moves (the fleet-global prefix
        # cache). ship_blocks=False reverts to per-replica caches —
        # the bench's baseline arm for the fleet-hit-rate comparison.
        self.ship_blocks = ship_blocks
        if isinstance(obs, RouterObs):
            self.obs = obs
        else:
            self.obs = RouterObs(enabled=bool(obs))
        # The fleet observability plane: router-side request spans
        # (merged with replica traces by fleet_trace()), the straggler
        # detector, and the flight recorder. All keyed off the obs
        # enable flag so the obs=False arm of the bench's
        # router_obs_overhead_pct A/B disables the WHOLE plane.
        self.trace = trace if trace is not None else RouterTrace(
            enabled=self.obs.enabled
        )
        if isinstance(anomaly, AnomalyDetector):
            self._anomaly = anomaly
        elif anomaly is False or not self.obs.enabled:
            self._anomaly = None
        else:
            self._anomaly = AnomalyDetector()
        self.anomaly_penalty = anomaly_penalty
        self.fleet_refresh_s = fleet_refresh_s
        if flight is not None:
            self.flight = flight
        elif self.obs.enabled:
            self.flight = FlightRecorder(flight_dir)
        else:
            self.flight = None
        self._penalty: dict[str, float] = {}
        self._fleet_refresh_at = 0.0
        self._rng = random.Random(seed)
        # Trace-id prefix: stable per router instance, drawn from the
        # seeded rng so replays are deterministic while two routers'
        # ids stay distinguishable.
        self._trace_prefix = f"w{self._rng.randrange(16 ** 6):06x}"
        self._handles: list[_Handle] = []
        self._seq = 0
        for replica in replicas:
            self.add_replica(replica)
        # template key -> handle (affinity map); entries for retired
        # handles are dropped lazily at lookup.
        self._affinity: dict[int, _Handle] = {}
        # template key -> handle whose trie last received the
        # template's blocks (by local prefill OR by an import): the
        # fleet-global prefix-cache directory the block-shipping plane
        # consults. In colocated mode it shadows the affinity map and
        # only diverges on a re-point (where the ship happens); in
        # disaggregated mode it is the only record of block locality.
        self._block_home: dict[int, _Handle] = {}
        # template key -> decode-stage handle (disaggregated mode):
        # decode placement is prefix-affine even though prefill
        # placement is pure load, so a template's shipped blocks pool
        # on one decode replica instead of spraying the fleet.
        self._decode_affinity: dict[int, _Handle] = {}
        self._rr_next = 0
        self._next_rid = 0
        # router rid -> (handle, local rid, trace id); completed
        # records land in _done keyed by router rid.
        self._routes: dict[int, tuple[_Handle, int, str]] = {}
        self._local: dict[tuple[int, int], int] = {}
        self._done: dict[int, dict] = {}
        # Wire bytes shipped through block transfers, keyed by tile
        # dtype — the router-side ledger behind
        # `router_xfer_bytes_total{dtype}`.
        self._xfer_bytes: dict[str, int] = {}
        # router rid -> affinity key, held while the request is in
        # flight: the disaggregated decode stage places a stream by
        # its template key at handoff time.
        self._decode_key: dict[int, int | None] = {}
        # Prefix tallies of replicas already retired, so the fleet hit
        # rate never loses history when a slice is returned.
        self._retired_hits = 0
        self._retired_lookups = 0
        # The shadow/canary plane: at most one canary handle, its
        # controller, the mirror sampling state (a deterministic
        # Bresenham accumulator over `canary_mirror`), and the
        # mirror-side bookkeeping — mirror locals map to (router rid,
        # capture rid) OUTSIDE self._local so mirror completions
        # never reach the user-facing _done.
        self.canary_mirror = float(canary_mirror)
        if not 0.0 <= self.canary_mirror <= 1.0:
            raise ValueError(
                f"canary_mirror must be in [0, 1]; "
                f"got {canary_mirror}"
            )
        self._canary_opts = dict(canary_opts or {})
        self._canary: _Handle | None = None
        self.canary_controller: CanaryController | None = None
        self._mirror_seen = 0
        self._mirror_local: dict[tuple[int, int], tuple[int, int]] = {}
        self._mirrored_rids: set[int] = set()
        self._reconciler = (
            Reconciler(
                provider, scale_policy, obs=self.obs,
                trace=self.trace,
            )
            if provider is not None else None
        )
        # Fleet-level capture plane (obs/capture.py): records routed
        # traffic at the router's own submit/collect seams — done
        # records add the routed replica. The fleet capture's header
        # has no engine fingerprint (replicas own those; an engine
        # capture is the token-exact replay artifact) — its records
        # pin WHAT arrived and WHERE it went, the incident timeline
        # the per-replica captures are replayed against. Caveat: an
        # unseeded sampled request's effective seed is assigned
        # replica-side (the local rid), so only the replica's own
        # capture pins it.
        self._capture = CaptureLog.coerce(capture)
        if self._capture is not None:
            fp = {
                "version": 1,
                "router": {
                    "policy": policy,
                    "replicas": [h.name for h in self._handles],
                },
            }
            fp["id"] = fingerprint_id(fp)
            self._capture.attach(fp)
        self._set_replica_gauges()

    # -- fleet membership ----------------------------------------------

    def add_replica(self, replica, role: str = "both") -> None:
        """Admit a replica. `role` splits the fleet into serving
        stages: "both" (the colocated default), "prefill" (takes new
        requests, hands streams off at first token), or "decode"
        (receives migrated streams only, never a cold submit). Any
        prefill/decode member flips the router into disaggregated
        two-stage placement.

        `role="canary"` registers the candidate-config replica of the
        shadow plane: it receives mirrored submits only (sampled at
        `canary_mirror`), is invisible to routing and every scale
        signal, and its paired completions feed the
        `CanaryController` verdict machine — at most one canary at a
        time (a rollout compares ONE candidate; the verdict retires
        or promotes it before the next)."""
        if role not in ("both", "prefill", "decode", "canary"):
            raise ValueError(
                f"role must be 'both', 'prefill', 'decode' or "
                f"'canary'; got {role!r}"
            )
        if role == "canary" and self._canary is not None:
            raise ValueError(
                "fleet already has a canary replica "
                f"({self._canary.name}); resolve its verdict first"
            )
        name = getattr(replica, "name", None) or f"r{self._seq}"
        self._seq += 1
        handle = _Handle(replica, name, role=role)
        self._handles.append(handle)
        if role == "canary":
            self._canary = handle
            self.canary_controller = CanaryController(
                obs=self.obs,
                trace=self.trace,
                flight=self.flight,
                canary_name=name,
                **self._canary_opts,
            )
            self.canary_controller.set_fingerprints(
                self._primary_fingerprint(),
                self._replica_fingerprint(replica),
            )
            self.trace.event(
                "canary_armed", time.monotonic(), canary=name,
                mirror=self.canary_mirror,
                gate=(
                    "digest_exact"
                    if self.canary_controller.gate_armed
                    else "latency_only"
                ),
            )
        self._set_replica_gauges()

    @staticmethod
    def _replica_fingerprint(replica) -> dict | None:
        """The replica's engine config fingerprint (PR 15), read
        through whichever surface the adapter has — None for adapters
        without one (bare fakes, old pods), which leaves the canary
        gate ARMED (the conservative default)."""
        read = getattr(replica, "config_fingerprint", None)
        if read is None:
            engine = getattr(replica, "engine", None)
            read = getattr(engine, "config_fingerprint", None)
        if read is None:
            return None
        try:
            return read()
        except Exception:  # noqa: BLE001 — telemetry read
            return None

    def _primary_fingerprint(self) -> dict | None:
        """First serving member's fingerprint — the baseline the
        canary's config delta is classified against."""
        for h in self._handles:
            if h.role == "canary":
                continue
            fp = self._replica_fingerprint(h.replica)
            if fp is not None:
                return fp
        return None

    @property
    def disaggregated(self) -> bool:
        return any(
            h.role in ("prefill", "decode") for h in self._handles
        )

    def start_drain(self, handle: _Handle, migrate: bool = True) -> None:
        """Stop routing to `handle` and ask its replica to drain
        (resident work finishes; the reconciler retires it once
        `has_work` goes False). When the replica supports live
        migration (in-process engines), its resident requests — mid-
        decode slots, mid-prefill entries, queued work — are
        evacuated to a peer immediately instead of running the drain
        down, so a scale-down stops paying for the victim the moment
        the decision lands; streams continue token-identically on the
        destination. Replicas without the seam (HTTP pods, fakes)
        keep the classic finish-resident-work drain."""
        handle.replica.drain()
        self._set_replica_gauges()
        if migrate and getattr(
            handle.replica, "supports_migration", False
        ):
            self._migrate_residents(handle)

    def retire(self, handle: _Handle) -> None:
        """Remove a fully drained handle from the fleet, folding its
        prefix tallies into the retired accumulators first so the
        fleet-level hit rate keeps its history."""
        self._collect(handle)  # final records, before the handle goes
        hits, lookups = handle.prefix_tallies()
        self._retired_hits += hits
        self._retired_lookups += lookups
        self._handles.remove(handle)
        self._affinity = {
            k: h for k, h in self._affinity.items() if h is not handle
        }
        self._block_home = {
            k: h for k, h in self._block_home.items()
            if h is not handle
        }
        self._decode_affinity = {
            k: h for k, h in self._decode_affinity.items()
            if h is not handle
        }
        # Drop EVERY per-replica series of the retired member (and
        # its federated cb_* series vanish with the handle): the last
        # values would otherwise export a dead member as live forever.
        for instrument in (
            self.obs.replica_saturation,
            self.obs.replica_anomaly,
            self.obs.replica_anomaly_score,
            self.obs.scrape_errors,
        ):
            for labels in instrument.labelsets():
                if labels.get("replica") == handle.name:
                    instrument.remove(labels)
        if self._anomaly is not None:
            self._anomaly.forget(handle.name)
        self._penalty.pop(handle.name, None)
        if handle is self._canary:
            # The controller outlives the handle: its terminal
            # verdict (and any divergence bundle path) stays readable
            # through stats()/debug surfaces after the reject drain.
            self._canary = None
            self._mirror_local = {
                k: v for k, v in self._mirror_local.items()
                if k[0] != id(handle)
            }
        self.trace.event(
            "retire", time.monotonic(), replica=handle.name
        )
        self._set_replica_gauges()

    def active_handles(self) -> list[_Handle]:
        """Serving members: non-draining, canary excluded — the ONE
        candidate set behind routing picks, reconciler pressure/idle
        signals, and fleet-capacity accounting, so shadow load is
        invisible to every scale decision by construction."""
        return [
            h for h in self._handles
            if not h.replica.draining and h.role != "canary"
        ]

    def draining_handles(self) -> list[_Handle]:
        return [h for h in self._handles if h.replica.draining]

    @property
    def replicas(self) -> list:
        return [h.replica for h in self._handles]

    # -- routing -------------------------------------------------------

    def _load(self, handle: _Handle) -> float:
        """Routing load: the replica's normalized load plus the
        anomaly penalty — a flagged straggler reads as proportionally
        hotter, so p2c and the affinity overload check both steer
        traffic away from it before its queue ever shows the damage
        (`router_replica_anomaly_score` scaled into
        [0, anomaly_penalty])."""
        return replica_load(handle.replica) + self._penalty.get(
            handle.name, 0.0
        )

    def _pick(self, key: int | None) -> tuple[_Handle, str]:
        candidates = self.active_handles()
        if self.disaggregated:
            # Two-stage placement, stage one: new requests land on
            # prefill-capable members by pure load — affinity is the
            # DECODE stage's concern (the stream follows its blocks
            # there at first token); pinning prefill too would
            # serialize a hot template's prefills on one replica for
            # no cache gain the block-shipping plane doesn't already
            # provide.
            candidates = [h for h in candidates if h.can_prefill()]
        if not candidates:
            self.obs.failed.inc(labels={"reason": "no_replica"})
            raise RuntimeError(
                "fleet has no active replica to route to"
            )
        if self.policy == "round_robin":
            handle = candidates[self._rr_next % len(candidates)]
            self._rr_next += 1
            return handle, "round_robin"
        if key is not None and not self.disaggregated:
            handle = self._affinity.get(key)
            if handle is not None and handle in candidates:
                load = self._load(handle)
                # Affinity yields only when the target is HOT *and*
                # the sampled alternative is meaningfully less loaded
                # THAN THE TARGET: a uniformly saturated fleet (every
                # engine's busy component pinned at 1.0 under full
                # load) gains nothing from moving and would pay a
                # cold prefill per migration. The gap is checked
                # against the actual migration destination, not the
                # fleet minimum — a lucky global minimum must not
                # green-light re-pointing to whatever two replicas
                # the sample happened to draw (possibly hotter than
                # the target itself).
                if load < self.affinity_overload:
                    return handle, "affinity"
                alt = self._two_choices(candidates)
                if (
                    load - self._load(alt)
                    >= self.affinity_imbalance
                ):
                    self._affinity[key] = alt
                    return alt, "p2c"
                return handle, "affinity"
        # Unknown key (or no affinity yet): two-choice placement; the
        # key (if any) points here so the template's stream follows
        # the blocks it is about to warm.
        handle = self._two_choices(candidates)
        if key is not None and not self.disaggregated:
            self._affinity[key] = handle
        return handle, "p2c"

    def _two_choices(self, candidates: list[_Handle]) -> _Handle:
        """Power-of-two-choices: two distinct candidates when the
        fleet has them, least loaded wins (Mitzenmacher's d=2 bound:
        near-best-of-all balance at O(1) probes)."""
        if len(candidates) == 1:
            return candidates[0]
        a, b = self._rng.sample(candidates, 2)
        return min((a, b), key=self._load)

    def submit(
        self,
        prompt,
        *,
        trace_id: str | None = None,
        enqueued_at: float | None = None,
        **kwargs,
    ) -> int:
        """Route one request; returns a ROUTER request id (replica
        rids are namespaced per replica and never leak). Replica-side
        validation errors (bad knobs, oversize) propagate to the
        caller after landing in `router_requests_failed_total` —
        client errors stay client errors whatever replica they hit.

        The router mints a `trace_id` per request (or adopts the
        caller's) and propagates it to the replica — the
        `X-Walkai-Trace` header over HTTP, a submit field in process
        — so the replica's engine spans and the router's
        route/queue/round-trip spans merge under one id in the fleet
        `/debug/trace`. `enqueued_at` is the front-end's enqueue time
        (serverouter's driver queue), rendered as the queue-wait
        span."""
        t_submit = time.monotonic()
        key = prefix_key(prompt)
        handle, arm = self._pick(key)
        if (
            key is not None
            and self.ship_blocks
            and self.policy != "round_robin"
        ):
            # Ship KV blocks, not requests: when placement moves a
            # template off the replica whose trie holds its blocks
            # (an affinity re-point in colocated mode; any load-pick
            # divergence in disaggregated mode), the router brokers
            # an export/import of the prompt's READY prefix blocks
            # BEFORE the submit lands — the destination admits the
            # request against a warm trie and skips the cold
            # prefill.
            home = self._block_home.get(key)
            if (
                home is not None
                and home is not handle
                and home in self._handles
            ):
                self._ship(home, handle, prompt)
            self._block_home[key] = handle
        rid = self._next_rid
        if trace_id is None:
            trace_id = f"{self._trace_prefix}-{rid:08x}"
        canary_live = (
            self._canary is not None
            and not self._canary.replica.draining
        )
        if (
            canary_live
            and kwargs.get("temperature")
            and kwargs.get("seed") is None
        ):
            # Mirror determinism: an unseeded sampled request's
            # effective seed is minted REPLICA-side (the local rid —
            # the PR 15 rid-defaulting rule), which primary and
            # mirror would mint differently. Pin it router-side while
            # a canary is armed so both streams draw the same PRNG
            # sequence; the capture records the pinned value, so
            # replays stay bit-exact.
            kwargs["seed"] = rid % (2 ** 31)
        try:
            local = handle.replica.submit(
                prompt, trace_id=trace_id, **kwargs
            )
        except ValueError:
            self.obs.failed.inc(labels={"reason": "bad_request"})
            raise
        t_routed = time.monotonic()
        self._next_rid += 1
        self._routes[rid] = (handle, local, trace_id)
        self._local[(id(handle), local)] = rid
        self._decode_key[rid] = key
        handle.routed += 1
        self.obs.submitted.inc()
        self.obs.routed.inc(labels={"policy": arm})
        self.trace.submit(
            rid, trace_id=trace_id, t_submit=t_submit,
            t_routed=t_routed, replica=handle.name, policy=arm,
            t_enqueue=enqueued_at, affinity_key=key,
        )
        if self._capture is not None:
            self._capture.record_submit(
                rid=rid,
                trace_id=trace_id,
                prompt=np.asarray(prompt).reshape(-1).tolist(),
                replica=handle.name,
                policy=arm,
                arrival_s=round(
                    self._capture.arrival_offset(t_submit), 6
                ),
                **{
                    k: kwargs.get(k)
                    for k in (
                        "max_new_tokens", "eos_id", "temperature",
                        "top_k", "top_p", "seed", "adapter",
                    )
                },
            )
        if canary_live and self._mirror_due():
            self._mirror_submit(rid, prompt, trace_id, kwargs)
        return rid

    # -- the shadow/canary plane ----------------------------------------

    def _mirror_due(self) -> bool:
        """Deterministic sampling at `canary_mirror`: a Bresenham
        accumulator (mirror when the running fraction's integer part
        advances) — exactly fraction*N of N submits mirror, with no
        RNG draw perturbing the routing rng's sequence."""
        f = self.canary_mirror
        if f <= 0.0:
            return False
        n = self._mirror_seen
        self._mirror_seen += 1
        return int((n + 1) * f) > int(n * f)

    def _mirror_submit(
        self, rid: int, prompt, trace_id: str, kwargs: dict
    ) -> None:
        """Fork the shadow copy: same prompt and knobs (the effective
        seed already pinned), its own trace id suffix so replica-side
        spans stay distinguishable, completion routed to the
        CanaryController instead of the user. A mirror failure never
        fails the primary — it lands as a mirror_error comparison."""
        canary = self._canary
        ctrl = self.canary_controller
        t0 = time.monotonic()
        try:
            local = canary.replica.submit(
                prompt, trace_id=f"{trace_id}-m", **kwargs
            )
        except Exception as err:  # noqa: BLE001 — shadow path
            ctrl.on_mirrored()
            ctrl.on_mirror(rid, {"error": str(err), "tokens": None})
            self._mirrored_rids.add(rid)
            self.trace.event(
                "canary_mirror_failed", t0, rid=rid,
                canary=canary.name, error=str(err),
            )
            return
        # The mirror's capture rows need their OWN rid (submit rows
        # key by rid at load; reusing the primary's would overwrite
        # it) — drawn from the same counter, marked mirrored so
        # load_capture drops them by default.
        mirror_rid = self._next_rid
        self._next_rid += 1
        self._mirror_local[(id(canary), local)] = (rid, mirror_rid)
        self._mirrored_rids.add(rid)
        ctrl.on_mirrored()
        self.trace.event(
            "canary_mirror", t0, rid=rid, canary=canary.name,
            trace_id=f"{trace_id}-m",
        )
        if self._capture is not None:
            self._capture.record_submit(
                rid=mirror_rid,
                trace_id=f"{trace_id}-m",
                prompt=np.asarray(prompt).reshape(-1).tolist(),
                replica=canary.name,
                policy="canary",
                mirrored=True,
                mirror_of=rid,
                arrival_s=round(
                    self._capture.arrival_offset(t0), 6
                ),
                **{
                    k: kwargs.get(k)
                    for k in (
                        "max_new_tokens", "eos_id", "temperature",
                        "top_k", "top_p", "seed", "adapter",
                    )
                },
            )

    # -- block shipping & live migration -------------------------------

    @staticmethod
    def _supports_blocks(handle: _Handle) -> bool:
        return (
            getattr(handle.replica, "export_blocks", None) is not None
            and getattr(handle.replica, "import_blocks", None)
            is not None
        )

    def _count_xfer_bytes(self, payload: dict) -> None:
        """Wire-byte accounting for one brokered transfer payload:
        decoded tile bytes per storage dtype (b64 carries 4 chars per
        3 bytes), into `router_xfer_bytes_total{dtype}` and the
        `stats()` tally — the measurement behind the int8 pools'
        claimed ~2x wire saving (scale-f32 tiles count under their
        own `float32` dtype, the honest denominator)."""
        per_dtype: dict[str, int] = {}
        for t in payload.get("tiles", []) + payload.get(
            "draft_tiles", []
        ):
            dtype_name = str(t.get("dtype", "unknown"))
            per_dtype[dtype_name] = (
                per_dtype.get(dtype_name, 0)
                + len(t.get("data", "")) * 3 // 4
            )
        for dtype_name, nbytes in per_dtype.items():
            if nbytes:
                self.obs.xfer_bytes.inc(
                    nbytes, labels={"dtype": dtype_name}
                )
                self._xfer_bytes[dtype_name] = (
                    self._xfer_bytes.get(dtype_name, 0) + nbytes
                )

    def _ship(self, src: _Handle, dst: _Handle, prompt) -> None:
        """Broker one prefix-block transfer: export the prompt's
        chain of block hashes from `src`, import into `dst`. Best
        effort — a replica pair without the seam (fakes, old pods) or
        a source whose blocks were evicted ships nothing, and a
        transport error never fails the request the ship was
        for (the destination just pays the cold prefill the ship
        would have saved)."""
        if not (
            self._supports_blocks(src) and self._supports_blocks(dst)
        ):
            return
        t0 = time.monotonic()
        try:
            payload = src.replica.export_blocks(chain_hashes(prompt))
            if not payload.get("blocks"):
                self.obs.xfer_ships.inc(labels={"outcome": "empty"})
                return
            # Bytes count at the export/import seam: the payload has
            # left the source whatever the import's fate.
            self._count_xfer_bytes(payload)
            result = dst.replica.import_blocks(payload)
        except Exception as err:  # noqa: BLE001 — transport seam
            self.obs.xfer_ships.inc(labels={"outcome": "error"})
            self.obs.xfer_failures.inc(labels={"kind": "ship"})
            self.trace.event(
                "ship_failed", time.monotonic(), src=src.name,
                dst=dst.name, error=str(err),
            )
            return
        imported = int(result.get("imported", 0))
        self.obs.xfer_ships.inc(labels={"outcome": "ok"})
        self.obs.xfer_blocks_shipped.inc(imported)
        self.trace.event(
            "ship_blocks", t0, src=src.name, dst=dst.name,
            offered=len(payload["blocks"]), imported=imported,
        )

    def _remap(self, src: _Handle, dst: _Handle, landed) -> None:
        """Re-point in-flight routes after a migration: each landed
        entry (the destination's `import_resident` return) is matched
        to its router rid by the trace id the router minted at
        submit, then the route and the reverse local-rid map move to
        the destination. Records, capture rows and `/generate`
        responses keep flowing under the SAME router rid — the caller
        never learns the stream moved."""
        by_trace = {
            tid: rid
            for rid, (h, _local, tid) in self._routes.items()
            if h is src
        }
        for entry in landed:
            rid = by_trace.get(entry.get("trace_id"))
            if rid is None:
                continue
            _old_handle, old_local, tid = self._routes[rid]
            self._local.pop((id(src), old_local), None)
            self._routes[rid] = (dst, entry["rid"], tid)
            self._local[(id(dst), entry["rid"])] = rid

    def _migrate_residents(self, handle: _Handle) -> None:
        """Drain-down evacuation: export EVERYTHING the draining
        replica owns (queued, mid-prefill, mid-decode) and land it on
        the least-loaded migration-capable peer. If no peer can take
        the payload (capacity precheck raises), it re-imports into
        the SOURCE — `import_resident` bypasses the drain gate — so a
        failed migration degrades to the classic finish-resident-work
        drain with zero dropped requests."""
        replica = handle.replica
        try:
            payload = replica.export_resident()
        except Exception:  # noqa: BLE001
            self.obs.xfer_failures.inc(labels={"kind": "migrate"})
            return
        moved = len(payload.get("migrate", ())) + len(
            payload.get("resubmit", ())
        )
        if not moved:
            return
        self._count_xfer_bytes(payload)
        targets = sorted(
            (
                h for h in self._handles
                if h is not handle
                and not h.replica.draining
                # Never evacuate real traffic ONTO the canary —
                # shadow capacity is not serving capacity.
                and h.role != "canary"
                and getattr(h.replica, "supports_migration", False)
            ),
            key=self._load,
        )
        for dst in targets:
            try:
                landed = dst.replica.import_resident(payload)
            except RuntimeError:
                continue
            self._remap(handle, dst, landed)
            self.obs.xfer_migrations.inc(
                len(landed), labels={"outcome": "moved"}
            )
            self.trace.event(
                "migrate_residents", time.monotonic(),
                src=handle.name, dst=dst.name, requests=len(landed),
            )
            return
        # No peer could take it: put the work back where it was.
        landed = replica.import_resident(payload)
        self._remap(handle, handle, landed)
        self.obs.xfer_migrations.inc(
            len(landed), labels={"outcome": "returned"}
        )
        self.trace.event(
            "migrate_returned", time.monotonic(), src=handle.name,
            requests=len(landed),
        )

    def _pick_decode(self, key: int | None) -> _Handle | None:
        """Stage-two placement: decode-capable, non-draining,
        migration-capable members, prefix-affine with the same
        overload/imbalance yield as stage-agnostic affinity — a hot
        decode replica sheds templates to a meaningfully cooler one
        and the map re-points."""
        candidates = [
            h for h in self._handles
            if h.can_decode()
            and not h.replica.draining
            and getattr(h.replica, "supports_migration", False)
        ]
        if not candidates:
            return None
        if key is not None:
            handle = self._decode_affinity.get(key)
            if handle is not None and handle in candidates:
                load = self._load(handle)
                if load < self.affinity_overload:
                    return handle
                alt = self._two_choices(candidates)
                if load - self._load(alt) >= self.affinity_imbalance:
                    self._decode_affinity[key] = alt
                    return alt
                return handle
        handle = self._two_choices(candidates)
        if key is not None:
            self._decode_affinity[key] = handle
        return handle

    def _decode_handoff(self) -> None:
        """Stage boundary of the disaggregated fleet, run every
        step: each prefill-only replica's decode-ready streams (first
        token committed — prefill work done) are exported one request
        at a time and imported into their decode placement, KV blocks
        and sampler state riding the payload; the route re-points so
        the stream's record flows from the decode replica under the
        original router rid. A failed import leaves the stream
        decoding on the prefill replica — correctness never depends
        on the handoff."""
        for handle in self._handles:
            if handle.role != "prefill":
                continue
            replica = handle.replica
            if not getattr(replica, "supports_migration", False):
                continue
            for local in replica.decode_ready_rids():
                rid = self._local.get((id(handle), local))
                if rid is None:
                    continue  # submitted around the router
                dst = self._pick_decode(self._decode_key.get(rid))
                if dst is None or dst is handle:
                    continue
                payload = replica.export_resident(only=[local])
                if not payload.get("migrate"):
                    continue
                self._count_xfer_bytes(payload)
                try:
                    landed = dst.replica.import_resident(payload)
                except RuntimeError:
                    # Destination had no capacity: the stream is
                    # already off the source's slots, so it goes
                    # straight back (import_resident bypasses any
                    # drain gate) and finishes where it started.
                    self.obs.xfer_failures.inc(
                        labels={"kind": "migrate"}
                    )
                    landed = replica.import_resident(payload)
                    self._remap(handle, handle, landed)
                    continue
                self._remap(handle, dst, landed)
                self.obs.xfer_migrations.inc(
                    len(landed), labels={"outcome": "decode"}
                )
                self.trace.event(
                    "decode_handoff", time.monotonic(),
                    src=handle.name, dst=dst.name,
                    trace_id=self._routes[rid][2],
                )

    # -- the drive loop ------------------------------------------------

    def _collect(self, handle: _Handle) -> None:
        if handle.role == "canary":
            self._collect_mirror(handle)
            return
        for local, record in handle.replica.drain_done_records().items():
            rid = self._local.pop((id(handle), local), None)
            if rid is None:
                continue  # a request submitted around the router
            route = self._routes.pop(rid, None)
            self._decode_key.pop(rid, None)
            record = dict(record)
            record["replica"] = handle.name
            # The router's minted id is authoritative (a replica that
            # echoes one echoes this same value; one that doesn't —
            # a bare fake, an old pod — still yields a correlatable
            # record).
            if route is not None:
                record["trace_id"] = route[2]
            self.trace.collected(rid, time.monotonic())
            if self._capture is not None:
                # A FAILED replica request (tokens None + error) must
                # not masquerade as a clean zero-token completion:
                # tokens/digest stay null and the error rides along —
                # the incident timeline is what this capture is FOR.
                tokens = record.get("tokens")
                self._capture.record_done(
                    rid=rid,
                    trace_id=record.get("trace_id"),
                    replica=handle.name,
                    tokens=list(tokens) if tokens is not None else None,
                    n_tokens=len(tokens) if tokens is not None else 0,
                    digest=(
                        token_digest(tokens)
                        if tokens is not None else None
                    ),
                    ttft_s=record.get("ttft_s"),
                    wall_s=record.get("wall_s"),
                    truncated=record.get("truncated", False),
                    fingerprint=record.get("fingerprint"),
                    adapter=record.get("adapter"),
                    error=record.get("error"),
                )
            self._done[rid] = record
            if (
                self.canary_controller is not None
                and rid in self._mirrored_rids
            ):
                self._mirrored_rids.discard(rid)
                self.canary_controller.on_primary(rid, record)

    def _collect_mirror(self, handle: _Handle) -> None:
        """Completion seam of the shadow plane: the canary's records
        feed the CanaryController (and the capture, marked mirrored)
        — never `self._done`, so a mirror completion can never reach
        the user."""
        ctrl = self.canary_controller
        for local, record in handle.replica.drain_done_records().items():
            pair = self._mirror_local.pop((id(handle), local), None)
            if pair is None:
                continue
            rid, mirror_rid = pair
            record = dict(record)
            record["replica"] = handle.name
            if self._capture is not None:
                tokens = record.get("tokens")
                self._capture.record_done(
                    rid=mirror_rid,
                    trace_id=record.get("trace_id"),
                    replica=handle.name,
                    mirrored=True,
                    tokens=list(tokens) if tokens is not None else None,
                    n_tokens=len(tokens) if tokens is not None else 0,
                    digest=(
                        token_digest(tokens)
                        if tokens is not None else None
                    ),
                    ttft_s=record.get("ttft_s"),
                    wall_s=record.get("wall_s"),
                    truncated=record.get("truncated", False),
                    fingerprint=record.get("fingerprint"),
                    adapter=record.get("adapter"),
                    error=record.get("error"),
                )
            if ctrl is not None:
                ctrl.on_mirror(rid, record)

    def _canary_tick(self) -> None:
        """Apply the verdict machine's output each step: evaluate on
        live pairs, then promote (flip to a full serving role, record
        the winning fingerprint) or reject (migrate-first drain with
        trace reason `canary_reject`; retired here once empty when no
        reconciler owns retirement)."""
        canary, ctrl = self._canary, self.canary_controller
        if canary is None or ctrl is None:
            return
        if canary.replica.draining:
            # Reject drain in flight. The reconciler retires drained
            # members when one exists; without one the router must,
            # or a rejected canary haunts the handle list forever.
            if (
                self._reconciler is None
                and not canary.replica.has_work
            ):
                self.retire(canary)
            return
        state = ctrl.evaluate()
        if state == "promote":
            canary.role = "both"
            self._canary = None
            self.trace.event(
                "canary_promote", time.monotonic(),
                canary=canary.name,
                fingerprint=ctrl.winning_fingerprint_id,
                reason=ctrl.verdict_reason,
            )
            self._set_replica_gauges()
        elif state == "reject":
            self.trace.event(
                "drain_start", time.monotonic(),
                replica=canary.name, reason="canary_reject",
                verdict=ctrl.verdict_reason,
            )
            self.start_drain(canary)

    def step(self) -> bool:
        """One fleet turn: advance every replica (draining ones
        included — their resident work is what a drain waits for),
        collect finished records, tick the reconciler, refresh the
        fleet gauges. True while any replica still has work."""
        for handle in list(self._handles):
            handle.replica.step()
            self._collect(handle)
        if self.disaggregated:
            self._decode_handoff()
        if self._reconciler is not None:
            self._reconciler.tick(self)
        self._canary_tick()
        self._refresh_gauges()
        return self.has_work

    def run(self) -> dict[int, list[int]]:
        """Drive until every routed request finishes."""
        out: dict[int, list[int]] = {}
        while self.has_work:
            self.step()
            out.update(self.drain_done())
        out.update(self.drain_done())
        return out

    @property
    def has_work(self) -> bool:
        return bool(self._routes) or any(
            h.replica.has_work for h in self._handles
        )

    def drain_done_records(self) -> dict[int, dict]:
        done, self._done = self._done, {}
        return done

    def drain_done(self) -> dict[int, list[int]]:
        return {
            rid: rec["tokens"]
            for rid, rec in self.drain_done_records().items()
        }

    # -- telemetry -----------------------------------------------------

    def _set_replica_gauges(self) -> None:
        active = [
            h for h in self._handles
            if not h.replica.draining and h.role != "canary"
        ]
        draining = [
            h for h in self._handles if h.replica.draining
        ]
        self.obs.replicas_gauge.set(
            len(active), labels={"state": "active"}
        )
        self.obs.replicas_gauge.set(
            len(draining), labels={"state": "draining"},
        )

    def _refresh_gauges(self) -> None:
        self._set_replica_gauges()
        self.obs.queue_depth.set(
            # Shadow load is invisible: the canary's mirrored queue
            # must not read as fleet admission pressure.
            sum(
                h.replica.queue_depth for h in self._handles
                if h.role != "canary"
            )
        )
        for handle in self._handles:
            sat = handle.replica.saturation
            if sat is not None:
                self.obs.replica_saturation.set(
                    sat, labels={"replica": handle.name}
                )
        rate = self.prefix_hit_rate
        if rate is not None:
            self.obs.prefix_hit_rate.set(round(rate, 4))
        # The fleet plane's heavier pass (per-replica signal reads,
        # anomaly scoring, scrape-error deltas, SLO-breach edges) is
        # throttled like the engine's SLO gauge refresh — its inputs
        # are windowed quantities that move on ~second scales, and
        # computing them per step would tax the driver loop for no
        # added signal.
        if not self.obs.enabled and self._anomaly is None:
            return
        now = time.monotonic()
        if now >= self._fleet_refresh_at:
            self._fleet_refresh_at = now + self.fleet_refresh_s
            self._refresh_fleet(now)

    def _refresh_fleet(self, now: float) -> None:
        handles = list(self._handles)
        # The anomaly/signal half of the plane reads ACTIVE replicas
        # only: a draining member serves no traffic, so its skewed
        # tail windows must neither flag it (a flight bundle per
        # scale-down) nor contaminate the leave-one-out peer median
        # the healthy replicas are judged against. The canary is
        # excluded the same way: its candidate config's different
        # timing profile must neither count as fleet capacity nor
        # contaminate the peer median a straggler verdict compares
        # against (the canary plane's latency windows are the right
        # place to judge it). Scrape-error accounting below still
        # covers every handle — a flapping pod's history matters
        # through its drain.
        active = [
            h for h in handles
            if not h.replica.draining and h.role != "canary"
        ]
        self.obs.fleet_capacity.set(sum(
            int(getattr(h.replica, "slots", 0) or 0) for h in active
        ))
        signals: dict[str, dict] = {}
        for handle in active:
            read = getattr(handle.replica, "obs_signals", None)
            sig = None
            if read is not None:
                try:
                    sig = read()
                except Exception:  # noqa: BLE001 — telemetry read
                    sig = None
            signals[handle.name] = sig or {}
        rooflines = [
            signals[h.name].get("roofline_fraction")
            for h in active
            if signals[h.name].get("roofline_fraction") is not None
        ]
        if len(rooflines) >= 2:
            self.obs.roofline_spread.set(
                round(max(rooflines) - min(rooflines), 4)
            )
        else:
            # Under two reporters the spread is undefined: drop the
            # series rather than exporting the last two-replica value
            # as a live "degraded shard" signal forever.
            self.obs.roofline_spread.remove()
        # Scrape-error deltas -> the labeled counter (the adapter
        # counts locally; the router reflects growth since its last
        # look, so counter semantics survive the polling shape).
        for handle in handles:
            read = getattr(
                handle.replica, "scrape_error_stats", None
            )
            if read is None:
                continue
            counts = (read() or {}).get("counts") or {}
            for kind, count in counts.items():
                seen = handle.scrape_seen.get(kind, 0)
                if count > seen:
                    self.obs.scrape_errors.inc(
                        count - seen,
                        labels={"replica": handle.name, "kind": kind},
                    )
                    handle.scrape_seen[kind] = count
        # Straggler scoring + flight-recorder triggers.
        if self._anomaly is not None:
            verdicts = self._anomaly.update(signals)
            for handle in handles:
                verdict = verdicts.get(handle.name) or {
                    "score": 0.0, "flagged": False,
                }
                was_flagged = handle.anomaly.get("flagged", False)
                handle.anomaly = verdict
                self.obs.replica_anomaly.set(
                    1.0 if verdict["flagged"] else 0.0,
                    labels={"replica": handle.name},
                )
                self.obs.replica_anomaly_score.set(
                    verdict["score"],
                    labels={"replica": handle.name},
                )
                # The load penalty is gated on the FLAG, then scaled
                # by the score: routing for a healthy fleet is
                # byte-identical to the pre-plane router (sub-flag
                # scores are expected timing spread, and a continuous
                # penalty would let CPU noise push an affinity target
                # over the overload check and migrate templates for
                # nothing), while a flagged straggler sheds share in
                # proportion to how sick it looks.
                self._penalty[handle.name] = (
                    self.anomaly_penalty
                    * min(
                        1.0,
                        max(0.0, verdict["score"])
                        / self._anomaly.threshold,
                    )
                ) if verdict["flagged"] else 0.0
                if verdict["flagged"] and not was_flagged:
                    self.trace.event(
                        "anomaly_flagged", now,
                        replica=handle.name,
                        score=verdict["score"],
                        signals=verdict.get("signals", {}),
                    )
                    self._flight_dump(
                        "anomaly", handle, now, signals,
                        extra={"anomaly": verdicts},
                    )
                elif was_flagged and not verdict["flagged"]:
                    self.trace.event(
                        "anomaly_cleared", now,
                        replica=handle.name,
                        score=verdict["score"],
                    )
        # Windowed SLO breach edges: dump once per False transition,
        # not once per breached tick (active members only — a
        # draining replica's tail breach is the drain, not news).
        for handle in active:
            ok = handle.replica.slo_ok
            if ok is False and not handle.slo_was_false:
                handle.slo_was_false = True
                self._flight_dump("slo_breach", handle, now, signals)
            elif ok is not False:
                handle.slo_was_false = False

    def _flight_dump(
        self,
        trigger: str,
        handle: _Handle,
        now: float,
        signals: dict,
        extra: dict | None = None,
    ) -> None:
        """One flight-recorder bundle: the suspect replica's
        debug_state, the fleet snapshot, every replica's windowed
        signals, and the recent router trace ring — captured AT the
        flip, because the state is gone by the time a human looks."""
        if self.flight is None:
            return
        debug_state = None
        read = getattr(handle.replica, "debug_state", None)
        if read is not None:
            try:
                debug_state = read()
            except Exception as e:  # noqa: BLE001 — best-effort capture
                debug_state = {"error": str(e)}
        payload = {
            "replica": handle.name,
            "at_unix_s": time.time(),
            "fleet": self.stats(),
            "window_signals": signals,
            "debug_state": debug_state,
            "trace_ring": self.trace.ring.snapshot()[-256:],
            **(extra or {}),
        }
        path = self.flight.dump(trigger, payload, now=now)
        if path is not None:
            self.obs.flight_dumps.inc(labels={"trigger": trigger})
            self.trace.event(
                "flight_dump", now, trigger=trigger,
                replica=handle.name, path=path,
            )

    def anomaly_flagged_names(self) -> list[str]:
        """Currently flagged replicas — the reconciler's drain-victim
        hint (a straggler is the first candidate to rotate out when
        the fleet scales down)."""
        return [
            h.name for h in self._handles
            if h.anomaly.get("flagged")
        ]

    def federated_metrics(self) -> str:
        """The serverouter `/metrics` body: the router's own
        `router_*` registry followed by every current replica's
        engine series re-exported under a `replica` label
        (`obs/federation.federate`). Retired replicas stop being
        sources, so their series drop from the very next render —
        the same dead-pods-never-export discipline as the
        per-replica gauges. Reads only registries (lock-guarded) and
        the adapters' cached scrapes, so a handler thread may call
        it beside the driver; an HTTP replica past its cache window
        pays one scrape here (federation caveats:
        docs/observability.md)."""
        own = self.obs.render()
        if not self.obs.enabled:
            return own
        sources: dict[str, str] = {}
        for handle in list(self._handles):
            read = getattr(handle.replica, "metrics_text", None)
            if read is None:
                continue
            try:
                text = read()
            except Exception:  # noqa: BLE001 — telemetry read
                continue
            if text:
                sources[handle.name] = text
        return own + federate(sources)

    def fleet_trace(self) -> dict:
        """The serverouter `/debug/trace` body: the router's spans
        merged with every current replica's Chrome export into one
        clock-aligned timeline (`obs/federation.merge_fleet_trace`;
        per-replica offsets come from each adapter's
        `clock_offset_s()` — the /healthz RTT-midpoint estimate for
        HTTP pods, exactly 0 in process)."""
        replicas = []
        for handle in list(self._handles):
            read = getattr(handle.replica, "chrome_trace", None)
            if read is None:
                continue
            try:
                trace = read()
            except Exception:  # noqa: BLE001 — debug read
                trace = None
            if not trace:
                continue
            offset = getattr(
                handle.replica, "clock_offset_s", None
            )
            replicas.append({
                "name": handle.name,
                "trace": trace,
                "offset_s": offset() if offset is not None else 0.0,
            })
        return merge_fleet_trace(self.trace.chrome_trace(), replicas)

    @property
    def prefix_hit_rate(self) -> float | None:
        """Fleet-level prefix-cache block hit rate: hits over
        lookupable blocks summed across live AND retired replicas —
        the metric prefix-affinity routing exists to raise."""
        hits, lookups = self._retired_hits, self._retired_lookups
        for handle in self._handles:
            h, lk = handle.prefix_tallies()
            hits += h
            lookups += lk
        return hits / lookups if lookups else None

    @property
    def capture(self) -> CaptureLog | None:
        """The fleet capture log (None when not armed) — the
        serverouter `/debug/capture` surface."""
        return self._capture

    def capture_stats(self) -> dict:
        """Fleet capture status — the serverouter `/debug/capture`
        payload (same shape as the engine's `capture_stats()`; the
        fleet header fingerprint id stands in for the engine's)."""
        if self._capture is None:
            return {"enabled": False, "fingerprint": None}
        fp = self._capture.fingerprint or {}
        return {
            "enabled": True,
            "fingerprint": fp.get("id"),
            **self._capture.stats(),
        }

    def scale_events(self) -> dict[str, int]:
        return {
            d: int(self.obs.scale_events.value(
                labels={"direction": d}
            ))
            for d in ("up", "down", "denied")
        }

    def stats(self) -> dict:
        """One fleet snapshot: membership, per-replica signals and
        routed counts, affinity-map size, fleet prefix hit rate, and
        the scale-event tallies — the serverouter `/healthz` fleet
        block and the traffic harness's read surface."""
        rate = self.prefix_hit_rate

        def scrape(h: _Handle):
            read = getattr(h.replica, "scrape_error_stats", None)
            return read() if read is not None else None

        return {
            **({} if self.obs.enabled else {"obs_disabled": True}),
            "policy": self.policy,
            "replicas": [
                {
                    "name": h.name,
                    "role": h.role,
                    "draining": h.replica.draining,
                    "saturation": h.replica.saturation,
                    "slo_ok": h.replica.slo_ok,
                    "queue_depth": h.replica.queue_depth,
                    "has_work": h.replica.has_work,
                    "routed": h.routed,
                    # Fleet plane: straggler verdict + (HTTP) scrape
                    # health — None for adapters without scrapes.
                    "anomaly": (
                        dict(h.anomaly)
                        if self._anomaly is not None else None
                    ),
                    "scrape": scrape(h),
                }
                for h in self._handles
            ],
            "active": len(self.active_handles()),
            "draining": len(self.draining_handles()),
            "affinity_keys": len(self._affinity),
            "prefix_hit_rate": (
                round(rate, 4) if rate is not None else None
            ),
            "scale_events": self.scale_events(),
            "in_flight": len(self._routes),
            "xfer_bytes": dict(self._xfer_bytes),
            "anomaly_flagged": self.anomaly_flagged_names(),
            "flight_dir": (
                self.flight.dir if self.flight is not None else None
            ),
            "canary": self.canary_stats(),
        }

    def canary_stats(self) -> dict | None:
        """The shadow plane's status — the serverouter `/debug/canary`
        payload (the controller's view plus the router-side mirror
        fraction and whether the canary handle still exists). Survives
        the canary's retirement: the terminal verdict, counters, and
        any divergence bundle path stay readable; None only when no
        canary was ever armed."""
        if self.canary_controller is None:
            return None
        return {
            "mirror_fraction": self.canary_mirror,
            "armed": self._canary is not None,
            **self.canary_controller.stats(),
        }

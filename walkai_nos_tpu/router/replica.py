"""Replica adapters: one interface over in-process and HTTP engines.

The fleet router (`router/core.py`) owns N serving replicas — one per
TPU slice — and needs exactly six things from each: submit a request,
advance it (in-process only), collect finished records, read its
scale signals (saturation / SLO compliance / queue depth), start a
graceful drain, and read its prefix-cache tallies so the fleet-level
`router_prefix_hit_rate` can be computed. Everything else (paging,
speculation, SLO windows) stays inside the engine.

Two adapters implement that surface:

- **`EngineReplica`** wraps a `models/serve.ContinuousBatcher`
  in-process — the CI / single-host shape, and what the traffic-replay
  harness (`sim/trafficbench.py`) drives. `step()` advances the
  engine one pipeline turn; drain maps to the engine's own
  `drain()` seam (new submits reject with the `draining` taxonomy
  reason, resident slots finish).
- **`HttpReplica`** fronts a remote demo-server pod
  (`demos/tpu-sharing-comparison/app/main.py`) over its existing
  endpoints: `POST /generate` per request (a small worker pool keeps
  submits non-blocking), `GET /healthz` for the engine block's
  `saturation` / `slo_ok` / `queue_depth` / `has_work` /
  `draining` scale signals (cached for `refresh_s` so hot routing
  paths don't serialize on probes), `GET /stats` for the
  `cb_prefix` tallies. `drain()` is router-side (stop routing here,
  wait for in-flight work) — the remote process keeps its own
  lifecycle.

Both expose the same attribute surface, so the router, the
autoscaling reconciler, and the traffic harness never branch on the
deployment shape.
"""

from __future__ import annotations

import json
import queue
import threading
import time
import urllib.request

__all__ = ["EngineReplica", "HttpReplica"]


class EngineReplica:
    """In-process replica over a `ContinuousBatcher`."""

    # In-process work only advances when step() is called, so a driver
    # loop must spin while this replica has work. HttpReplica's work
    # advances remotely — its driver can sleep between collection
    # ticks instead of burning a core.
    steps_locally = True

    def __init__(self, engine, *, name: str = "engine"):
        self.name = name
        self.engine = engine

    def warm(self) -> None:
        """Compile the engine's serving programs before traffic (the
        engine's own pow2 admission-burst discipline — a cold engine
        pays ~seconds of XLA compile on its FIRST concurrent
        admissions, mid-traffic)."""
        self.engine.warm()

    # -- request path --------------------------------------------------

    def submit(self, prompt, **kwargs) -> int:
        return self.engine.submit(prompt, **kwargs)

    def step(self) -> None:
        if self.engine.has_work:
            self.engine.step()

    def drain_done_records(self) -> dict[int, dict]:
        return self.engine.drain_done_records()

    # -- scale signals -------------------------------------------------

    @property
    def saturation(self):
        return self.engine.saturation

    @property
    def slo_ok(self):
        return self.engine.slo_ok

    @property
    def queue_depth(self) -> int:
        return self.engine.queue_depth

    @property
    def has_work(self) -> bool:
        return self.engine.has_work

    @property
    def slots(self) -> int:
        return self.engine.slots

    # -- drain lifecycle -----------------------------------------------

    def drain(self) -> None:
        self.engine.drain()

    @property
    def draining(self) -> bool:
        return self.engine.draining

    # -- fleet telemetry -----------------------------------------------

    def prefix_stats(self) -> dict:
        return self.engine.prefix_stats()


class HttpReplica:
    """Remote replica over the demo server's HTTP surface.

    `submit()` enqueues; a small worker pool POSTs `/generate` and
    parks each response as a finished record, so the router's submit
    path never blocks on a remote generation. Records carry the same
    keys the engine's `drain_done_records()` produces ("tokens",
    "ttft_s", "wall_s", "truncated") plus "error" on failure, so the
    router's completion path is adapter-agnostic.
    """

    # The remote server drives its own engine; a driver fronting only
    # HTTP replicas sleeps between ticks (see EngineReplica).
    steps_locally = False

    def __init__(
        self,
        base_url: str,
        *,
        name: str | None = None,
        workers: int = 8,
        timeout_s: float = 120.0,
        refresh_s: float = 1.0,
    ):
        self.base_url = base_url.rstrip("/")
        self.name = name or self.base_url
        self._timeout_s = timeout_s
        self._refresh_s = refresh_s
        self._next_rid = 0
        self._lock = threading.Lock()
        self._queue: queue.Queue = queue.Queue()
        self._inflight = 0
        self._done: dict[int, dict] = {}
        self._draining = False
        self._health: dict | None = None
        self._health_at: float | None = None
        self._unreachable = False
        self._prefix: dict = {}
        self._prefix_at: float | None = None
        for i in range(max(1, workers)):
            threading.Thread(
                target=self._worker, daemon=True,
                name=f"router-replica-{self.name}-{i}",
            ).start()

    # -- request path --------------------------------------------------

    def submit(self, prompt, **kwargs) -> int:
        body = {"prompt": [int(t) for t in prompt]}
        for key in (
            "max_new_tokens", "eos_id", "temperature", "top_k",
            "top_p", "seed",
        ):
            if kwargs.get(key) is not None:
                body[key] = kwargs[key]
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
            self._inflight += 1
        self._queue.put((rid, body))
        return rid

    def _worker(self) -> None:
        while True:
            rid, body = self._queue.get()
            t0 = time.monotonic()
            try:
                req = urllib.request.Request(
                    f"{self.base_url}/generate",
                    data=json.dumps(body).encode(),
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(
                    req, timeout=self._timeout_s
                ) as resp:
                    out = json.loads(resp.read())
                record = {
                    "tokens": out.get("tokens", []),
                    "ttft_s": out.get(
                        "ttft_seconds",
                        out.get("generate_time_seconds", 0.0),
                    ),
                    "wall_s": out.get(
                        "engine_wall_seconds",
                        time.monotonic() - t0,
                    ),
                    "truncated": out.get("truncated", False),
                }
            except Exception as e:  # noqa: BLE001 — per-request failure
                record = {
                    "tokens": None,
                    "ttft_s": None,
                    "wall_s": time.monotonic() - t0,
                    "truncated": False,
                    "error": str(e),
                }
            with self._lock:
                self._done[rid] = record
                self._inflight -= 1

    def warm(self) -> None:
        """No-op: the remote server warms its own engine at startup."""

    def step(self) -> None:
        """No-op: the remote server drives its own engine."""

    def drain_done_records(self) -> dict[int, dict]:
        with self._lock:
            done, self._done = self._done, {}
        return done

    # -- scale signals (cached /healthz engine block) ------------------

    def _engine_block(self) -> dict:
        now = time.monotonic()
        if (
            self._health_at is None
            or now - self._health_at >= self._refresh_s
        ):
            try:
                # Short probe timeout: this runs on the ROUTER's
                # driver thread (load reads inside routing picks) — a
                # blackholed pod must not stall the whole fleet's
                # request path for long per refresh interval.
                with urllib.request.urlopen(
                    f"{self.base_url}/healthz", timeout=2.0
                ) as resp:
                    payload = json.loads(resp.read())
                self._health = payload.get("engine") or {}
                self._unreachable = False
            except Exception:  # noqa: BLE001 — probe failed
                self._health = None
                self._unreachable = True
            self._health_at = now
        return self._health or {}

    @property
    def unreachable(self) -> bool:
        """True while the last health probe FAILED (distinct from
        'not yet probed'). `autoscale.replica_load` reads this as
        maximum load, so routing prefers any replica that answers —
        an empty engine block would otherwise score a DEAD pod as
        load 0.0, the fleet's most attractive target."""
        self._engine_block()  # refresh if the cache expired
        return self._unreachable

    @property
    def saturation(self):
        return self._engine_block().get("saturation")

    @property
    def slo_ok(self):
        return self._engine_block().get("slo_ok")

    @property
    def queue_depth(self) -> int:
        return int(self._engine_block().get("queue_depth") or 0)

    @property
    def has_work(self) -> bool:
        with self._lock:
            if self._inflight > 0:
                return True
        return bool(self._engine_block().get("has_work"))

    @property
    def slots(self) -> int:
        return int(self._engine_block().get("slots") or 1)

    # -- drain lifecycle -----------------------------------------------

    def drain(self) -> None:
        """Router-side drain: stop routing here; `has_work` (local
        in-flight requests OR the remote engine block) reports when
        the replica can be retired. The remote process's own drain is
        its operator's call — the router only stops feeding it."""
        self._draining = True

    @property
    def draining(self) -> bool:
        return self._draining

    # -- fleet telemetry -----------------------------------------------

    def prefix_stats(self) -> dict:
        """Cached for `refresh_s`, like the /healthz probe: the
        router reads prefix tallies every step (the fleet hit-rate
        gauge), and an uncached synchronous GET per step per replica
        would let one slow replica stall the whole driver loop."""
        now = time.monotonic()
        if (
            self._prefix_at is not None
            and now - self._prefix_at < self._refresh_s
        ):
            return self._prefix
        try:
            with urllib.request.urlopen(
                f"{self.base_url}/stats", timeout=5.0
            ) as resp:
                payload = json.loads(resp.read())
            self._prefix = payload.get("cb_prefix") or {}
        except Exception:  # noqa: BLE001 — telemetry must not gate routing
            pass  # keep the last good tallies
        self._prefix_at = now
        return self._prefix

"""Replica adapters: one interface over in-process and HTTP engines.

The fleet router (`router/core.py`) owns N serving replicas — one per
TPU slice — and needs the same surface from each: submit a request
(carrying the router-minted trace id), advance it (in-process only),
collect finished records, read its scale signals (saturation / SLO
compliance / queue depth), start a graceful drain, and read its
fleet-plane telemetry — prefix tallies for `router_prefix_hit_rate`,
the rendered `cb_*` exposition the serverouter federates under a
`replica` label, the windowed straggler signals `obs/anomaly.py`
scores, and the Chrome trace export (plus a clock offset) the fleet
`/debug/trace` merges. Everything else (paging, speculation, SLO
windows) stays inside the engine.

Two adapters implement that surface:

- **`EngineReplica`** wraps a `models/serve.ContinuousBatcher`
  in-process — the CI / single-host shape, and what the traffic-replay
  harness (`sim/trafficbench.py`) drives. `step()` advances the
  engine one pipeline turn; drain maps to the engine's own
  `drain()` seam (new submits reject with the `draining` taxonomy
  reason, resident slots finish). Its clock IS the router's clock, so
  `clock_offset_s()` is 0.0 by construction.
- **`HttpReplica`** fronts a remote demo-server pod
  (`demos/tpu-sharing-comparison/app/main.py`) over its existing
  endpoints: `POST /generate` per request (a small worker pool keeps
  submits non-blocking; the trace id rides the `X-Walkai-Trace`
  header), `GET /healthz` for the engine block's scale signals
  (cached for `refresh_s` so hot routing paths don't serialize on
  probes — and doubling as the NTP-style clock-offset estimate: the
  payload's `monotonic_s` minus the probe's RTT midpoint), `GET
  /stats` for the `cb_prefix` tallies, `GET /metrics` for the
  federated exposition + straggler signals, `GET /debug/trace` /
  `/debug/state` on demand. Scrape FAILURES are counted per endpoint
  kind (`scrape_error_stats()` → the router's
  `router_replica_scrape_errors_total{replica,kind}`) instead of
  being swallowed — a flapping pod used to read only as
  "unreachable" with no history. `drain()` is router-side (stop
  routing here, wait for in-flight work) — the remote process keeps
  its own lifecycle.

Both expose the same attribute surface, so the router, the
autoscaling reconciler, and the traffic harness never branch on the
deployment shape.
"""

from __future__ import annotations

import json
import queue
import threading
import time
import urllib.request

from walkai_nos_tpu.obs.federation import first_value

__all__ = ["EngineReplica", "HttpReplica"]


class EngineReplica:
    """In-process replica over a `ContinuousBatcher`."""

    # In-process work only advances when step() is called, so a driver
    # loop must spin while this replica has work. HttpReplica's work
    # advances remotely — its driver can sleep between collection
    # ticks instead of burning a core.
    steps_locally = True

    def __init__(self, engine, *, name: str = "engine"):
        self.name = name
        self.engine = engine

    def warm(self) -> None:
        """Compile the engine's serving programs before traffic (the
        engine's own pow2 admission-burst discipline — a cold engine
        pays ~seconds of XLA compile on its FIRST concurrent
        admissions, mid-traffic)."""
        self.engine.warm()

    # -- request path --------------------------------------------------

    def submit(self, prompt, **kwargs) -> int:
        return self.engine.submit(prompt, **kwargs)

    def step(self) -> None:
        if self.engine.has_work:
            self.engine.step()

    def drain_done_records(self) -> dict[int, dict]:
        return self.engine.drain_done_records()

    # -- KV block transfer / live migration ----------------------------

    # In-process engines carry the full transfer plane: prefix blocks
    # ship between tries, and resident requests (KV + sampler state)
    # migrate wholesale. HTTP replicas ship blocks over /blocks but
    # never migrate requests — the response socket lives on the
    # source pod.
    supports_migration = True

    def export_blocks(self, hashes) -> dict:
        return self.engine.export_blocks(hashes)

    def import_blocks(self, payload) -> dict:
        return self.engine.import_blocks(payload)

    def export_resident(self, only=None) -> dict:
        return self.engine.export_resident(only=only)

    def import_resident(self, payload) -> list[dict]:
        return self.engine.import_resident(payload)

    def decode_ready_rids(self) -> list[int]:
        return self.engine.decode_ready_rids()

    def drain_stats(self) -> dict:
        return self.engine.drain_stats()

    # -- scale signals -------------------------------------------------

    @property
    def saturation(self):
        return self.engine.saturation

    @property
    def slo_ok(self):
        return self.engine.slo_ok

    @property
    def queue_depth(self) -> int:
        return self.engine.queue_depth

    @property
    def has_work(self) -> bool:
        return self.engine.has_work

    @property
    def slots(self) -> int:
        return self.engine.slots

    # -- drain lifecycle -----------------------------------------------

    def drain(self) -> None:
        self.engine.drain()

    @property
    def draining(self) -> bool:
        return self.engine.draining

    # -- fleet telemetry -----------------------------------------------

    def prefix_stats(self) -> dict:
        return self.engine.prefix_stats()

    def metrics_text(self) -> str:
        """The engine's own Prometheus exposition — the source the
        serverouter's federated `/metrics` re-labels per replica."""
        return self.engine.obs.render()

    def obs_signals(self) -> dict:
        """The straggler signals `obs/anomaly.py` scores against the
        fleet: windowed dispatch p99 (SLO window), device-attributed
        step ms, and the live roofline fraction (None off-TPU)."""
        slo = self.engine.slo_stats()
        attrib = self.engine.attrib_stats()
        dispatch = (slo.get("windows") or {}).get("dispatch") or {}
        return {
            "dispatch_p99_s": dispatch.get("p99"),
            "device_step_ms": attrib.get("device_step_ms"),
            "roofline_fraction": attrib.get("roofline_fraction"),
        }

    def chrome_trace(self) -> dict:
        """The engine's Chrome trace export (carries its clock origin
        for the fleet merge)."""
        return self.engine.obs.trace.chrome_trace()

    def clock_offset_s(self) -> float:
        """In-process: same monotonic clock as the router."""
        return 0.0

    def debug_state(self) -> dict:
        return self.engine.debug_state()


class HttpReplica:
    """Remote replica over the demo server's HTTP surface.

    `submit()` enqueues; a small worker pool POSTs `/generate` and
    parks each response as a finished record, so the router's submit
    path never blocks on a remote generation. Records carry the same
    keys the engine's `drain_done_records()` produces ("tokens",
    "ttft_s", "wall_s", "truncated", "trace_id", "fingerprint") plus
    "error" on failure, so the router's completion path is
    adapter-agnostic.
    """

    # The remote server drives its own engine; a driver fronting only
    # HTTP replicas sleeps between ticks (see EngineReplica).
    steps_locally = False

    def __init__(
        self,
        base_url: str,
        *,
        name: str | None = None,
        workers: int = 8,
        timeout_s: float = 120.0,
        refresh_s: float = 1.0,
    ):
        self.base_url = base_url.rstrip("/")
        self.name = name or self.base_url
        self._timeout_s = timeout_s
        self._refresh_s = refresh_s
        self._next_rid = 0
        self._lock = threading.Lock()
        self._queue: queue.Queue = queue.Queue()
        self._inflight = 0
        self._done: dict[int, dict] = {}
        self._draining = False
        self._health: dict | None = None
        self._health_at: float | None = None
        self._unreachable = False
        self._prefix: dict = {}
        self._prefix_at: float | None = None
        self._metrics_text = ""
        self._metrics_at: float | None = None
        self._clock_offset_s: float | None = None
        # Scrape-failure accounting (satellite of the fleet plane): a
        # flapping pod must show up as a counted, dated error stream,
        # not just as "unreachable right now".
        self._scrape_errors = {"healthz": 0, "stats": 0, "metrics": 0}
        self.last_error: str | None = None
        self._last_ok_at: float | None = None
        for i in range(max(1, workers)):
            threading.Thread(
                target=self._worker, daemon=True,
                name=f"router-replica-{self.name}-{i}",
            ).start()

    def _scrape_failed(self, kind: str, error: Exception) -> None:
        with self._lock:
            self._scrape_errors[kind] += 1
            self.last_error = f"{kind}: {error}"

    def _scrape_ok(self) -> None:
        with self._lock:
            self._last_ok_at = time.monotonic()

    def scrape_error_stats(self) -> dict:
        """Per-handle scrape health for `router.stats()` and the
        `router_replica_scrape_errors_total{replica,kind}` counter:
        cumulative failure counts by endpoint kind, the last error
        string, and how long ago ANY scrape last succeeded."""
        with self._lock:
            last_ok = self._last_ok_at
            return {
                "counts": dict(self._scrape_errors),
                "last_error": self.last_error,
                "last_ok_age_s": (
                    None if last_ok is None
                    else round(time.monotonic() - last_ok, 3)
                ),
            }

    # -- request path --------------------------------------------------

    def submit(self, prompt, **kwargs) -> int:
        trace_id = kwargs.pop("trace_id", None)
        body = {"prompt": [int(t) for t in prompt]}
        for key in (
            "max_new_tokens", "eos_id", "temperature", "top_k",
            "top_p", "seed", "adapter",
        ):
            if kwargs.get(key) is not None:
                body[key] = kwargs[key]
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
            self._inflight += 1
        self._queue.put((rid, body, trace_id))
        return rid

    def _worker(self) -> None:
        while True:
            rid, body, trace_id = self._queue.get()
            t0 = time.monotonic()
            try:
                headers = {"Content-Type": "application/json"}
                if trace_id is not None:
                    # The cross-process propagation contract: the demo
                    # server stores the id on the engine submit and
                    # echoes it on the response, so this request's
                    # engine spans and the router's spans share it.
                    headers["X-Walkai-Trace"] = str(trace_id)
                req = urllib.request.Request(
                    f"{self.base_url}/generate",
                    data=json.dumps(body).encode(),
                    headers=headers,
                )
                with urllib.request.urlopen(
                    req, timeout=self._timeout_s
                ) as resp:
                    out = json.loads(resp.read())
                record = {
                    "tokens": out.get("tokens", []),
                    "ttft_s": out.get(
                        "ttft_seconds",
                        out.get("generate_time_seconds", 0.0),
                    ),
                    "wall_s": out.get(
                        "engine_wall_seconds",
                        time.monotonic() - t0,
                    ),
                    "truncated": out.get("truncated", False),
                    "trace_id": out.get("trace_id", trace_id),
                    # The replica engine's config-fingerprint id
                    # (capture-armed pods only): matches this
                    # completion to the replica capture that can
                    # replay it.
                    "fingerprint": out.get("fingerprint"),
                    # Which LoRA adapter served it (0/absent = base)
                    # — the per-tenant attribution seam (item 2(b)).
                    "adapter": out.get("adapter"),
                }
            except Exception as e:  # noqa: BLE001 — per-request failure
                record = {
                    "tokens": None,
                    "ttft_s": None,
                    "wall_s": time.monotonic() - t0,
                    "truncated": False,
                    "trace_id": trace_id,
                    "error": str(e),
                }
            with self._lock:
                self._done[rid] = record
                self._inflight -= 1

    def warm(self) -> None:
        """No-op: the remote server warms its own engine at startup."""

    def step(self) -> None:
        """No-op: the remote server drives its own engine."""

    # -- KV block transfer (POST /blocks) ------------------------------

    # Prefix blocks ship fine over HTTP (content-addressed, b64 tiles)
    # but resident-request migration stays in-process only: the
    # response socket for an in-flight /generate lives on the source
    # pod, so moving its stream would orphan the client.
    supports_migration = False

    def _post_blocks(self, body: dict) -> dict:
        req = urllib.request.Request(
            f"{self.base_url}/blocks",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(
            req, timeout=self._timeout_s
        ) as resp:
            return json.loads(resp.read())

    def export_blocks(self, hashes) -> dict:
        """Ask the pod to serialize the named prefix blocks (the
        engine's `export_blocks` payload, JSON-clean by
        construction)."""
        return self._post_blocks(
            {"action": "export", "hashes": list(hashes)}
        )

    def import_blocks(self, payload) -> dict:
        """Land an exported payload in the pod's pool + trie; returns
        the engine's `{"imported": n, "rejected": {...}}` result."""
        return self._post_blocks(
            {"action": "import", "payload": payload}
        )

    def drain_done_records(self) -> dict[int, dict]:
        with self._lock:
            done, self._done = self._done, {}
        return done

    # -- scale signals (cached /healthz engine block) ------------------

    def _engine_block(self) -> dict:
        now = time.monotonic()
        if (
            self._health_at is None
            or now - self._health_at >= self._refresh_s
        ):
            try:
                # Short probe timeout: this runs on the ROUTER's
                # driver thread (load reads inside routing picks) — a
                # blackholed pod must not stall the whole fleet's
                # request path for long per refresh interval.
                t_send = time.monotonic()
                with urllib.request.urlopen(
                    f"{self.base_url}/healthz", timeout=2.0
                ) as resp:
                    payload = json.loads(resp.read())
                t_recv = time.monotonic()
                self._health = payload.get("engine") or {}
                self._unreachable = False
                self._scrape_ok()
                # NTP-style clock offset (replica monotonic minus the
                # router's), estimated at the probe's RTT midpoint —
                # the alignment the fleet /debug/trace merge uses.
                remote = payload.get("monotonic_s")
                if isinstance(remote, (int, float)):
                    self._clock_offset_s = (
                        float(remote) - (t_send + t_recv) / 2.0
                    )
            except Exception as e:  # noqa: BLE001 — probe failed
                self._health = None
                self._unreachable = True
                self._scrape_failed("healthz", e)
            self._health_at = now
        return self._health or {}

    @property
    def unreachable(self) -> bool:
        """True while the last health probe FAILED (distinct from
        'not yet probed'). `autoscale.replica_load` reads this as
        maximum load, so routing prefers any replica that answers —
        an empty engine block would otherwise score a DEAD pod as
        load 0.0, the fleet's most attractive target."""
        self._engine_block()  # refresh if the cache expired
        return self._unreachable

    @property
    def saturation(self):
        return self._engine_block().get("saturation")

    @property
    def slo_ok(self):
        return self._engine_block().get("slo_ok")

    @property
    def queue_depth(self) -> int:
        return int(self._engine_block().get("queue_depth") or 0)

    @property
    def has_work(self) -> bool:
        with self._lock:
            if self._inflight > 0:
                return True
        return bool(self._engine_block().get("has_work"))

    @property
    def slots(self) -> int:
        return int(self._engine_block().get("slots") or 1)

    # -- drain lifecycle -----------------------------------------------

    def drain(self) -> None:
        """Router-side drain: stop routing here; `has_work` (local
        in-flight requests OR the remote engine block) reports when
        the replica can be retired. The remote process's own drain is
        its operator's call — the router only stops feeding it."""
        self._draining = True

    @property
    def draining(self) -> bool:
        return self._draining

    # -- fleet telemetry -----------------------------------------------

    def prefix_stats(self) -> dict:
        """Cached for `refresh_s`, like the /healthz probe: the
        router reads prefix tallies every step (the fleet hit-rate
        gauge), and an uncached synchronous GET per step per replica
        would let one slow replica stall the whole driver loop."""
        now = time.monotonic()
        if (
            self._prefix_at is not None
            and now - self._prefix_at < self._refresh_s
        ):
            return self._prefix
        try:
            with urllib.request.urlopen(
                f"{self.base_url}/stats", timeout=5.0
            ) as resp:
                payload = json.loads(resp.read())
            self._prefix = payload.get("cb_prefix") or {}
            self._scrape_ok()
        except Exception as e:  # noqa: BLE001 — telemetry must not gate routing
            self._scrape_failed("stats", e)
            # keep the last good tallies
        self._prefix_at = now
        return self._prefix

    def metrics_text(self) -> str:
        """The pod's `/metrics` text, cached for `refresh_s` (the
        federation source AND the straggler-signal source). Failures
        keep the last good payload — a blip must not blank the whole
        fleet exposition — and count under kind="metrics"."""
        now = time.monotonic()
        if (
            self._metrics_at is not None
            and now - self._metrics_at < self._refresh_s
        ):
            return self._metrics_text
        try:
            # Same short-timeout discipline as the /healthz probe:
            # this runs on the ROUTER's driver thread (the straggler
            # signals are read inside the fleet refresh) — a
            # blackholed pod must not stall the whole fleet's request
            # path for long per refresh interval.
            with urllib.request.urlopen(
                f"{self.base_url}/metrics", timeout=2.0
            ) as resp:
                self._metrics_text = resp.read().decode()
            self._scrape_ok()
        except Exception as e:  # noqa: BLE001 — telemetry must not gate routing
            self._scrape_failed("metrics", e)
        self._metrics_at = now
        return self._metrics_text

    def obs_signals(self) -> dict:
        """Straggler signals parsed from the cached `/metrics` text
        (the same scrape the federation serves — no extra request)."""
        text = self.metrics_text()
        return {
            "dispatch_p99_s": first_value(text, "cb_slo_dispatch_p99"),
            "device_step_ms": first_value(text, "cb_device_step_ms"),
            "roofline_fraction": first_value(
                text, "cb_device_roofline_fraction"
            ),
        }

    def chrome_trace(self) -> dict | None:
        """The pod's `/debug/trace` export, fetched on demand (only
        the fleet `/debug/trace` endpoint asks). None on failure —
        the merge lists the replica under `skipped` instead of
        failing the whole timeline."""
        try:
            with urllib.request.urlopen(
                f"{self.base_url}/debug/trace", timeout=5.0
            ) as resp:
                return json.loads(resp.read())
        except Exception:  # noqa: BLE001 — debug read, best-effort
            return None

    def clock_offset_s(self) -> float:
        """Replica monotonic clock minus the router's, from the last
        successful health probe (0.0 until one lands)."""
        self._engine_block()
        return self._clock_offset_s or 0.0

    def debug_state(self) -> dict | None:
        """One `/debug/state` snapshot for the flight recorder;
        best-effort with a short timeout (a dump must never hang the
        driver on a sick pod — the sick pod is exactly when dumps
        fire)."""
        try:
            with urllib.request.urlopen(
                f"{self.base_url}/debug/state", timeout=2.0
            ) as resp:
                return json.loads(resp.read())
        except Exception:  # noqa: BLE001 — debug read, best-effort
            return None

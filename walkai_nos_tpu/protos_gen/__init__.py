"""Generated protobuf messages (protoc --python_out over `protos/`).

Regenerate with:
    protoc --python_out=walkai_nos_tpu/protos_gen -I protos \
        protos/podresources.proto protos/deviceplugin.proto

gRPC stubs are hand-written (no grpc_tools dependency):
`walkai_nos_tpu/resource/lister.py` (pod-resources client),
`walkai_nos_tpu/deviceplugin/` (device-plugin server + registration).
"""

"""Cluster TPU-inventory snapshots (the clusterinfo exporter's payload)."""

from walkai_nos_tpu.clusterinfo.collector import Collector  # noqa: F401
from walkai_nos_tpu.clusterinfo.types import (  # noqa: F401
    PodSummary,
    Snapshot,
    TpuInventory,
)

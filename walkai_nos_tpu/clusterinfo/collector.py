"""Cluster TPU inventory + pod summaries (`pkg/clusterinfo/collector.go` port).

Two inventory paths, like the reference (`collector.go:88-138`):
- primary: nodes managed by this control plane carry `status-tpu-*`
  annotations — aggregate used/free per profile from them (`:95-111`);
- fallback: unmanaged TPU nodes — derive from node capacity
  (`walkai.io/tpu-*` or whole-host `google.com/tpu`) minus summed pod
  requests (`:113-138`).

Pod summaries derive status from container states, then phase
(`:190-204`); start time from status, finish time only for terminal pods
(`:206-233`); profiles formatted `"2x2 x2"` (`:269-291`). Clock is
injectable (`:34-61` test seam).
"""

from __future__ import annotations

from datetime import datetime, timezone
from typing import Callable, Mapping

from walkai_nos_tpu.clusterinfo.types import PodSummary, Snapshot, TpuInventory
from walkai_nos_tpu.kube import objects
from walkai_nos_tpu.kube.client import KubeClient
from walkai_nos_tpu.tpu import topology
from walkai_nos_tpu.tpu.annotations import (
    AnnotationParseError,
    parse_node_annotations,
)
from walkai_nos_tpu.tpu.device import DeviceStatus
from walkai_nos_tpu.tpu.tiling.profile import (
    get_requested_profiles,
    is_slice_resource,
    extract_profile_name,
)
from walkai_nos_tpu.api import constants
from walkai_nos_tpu.utils.quantity import parse_quantity


def _utc_now() -> datetime:
    return datetime.now(timezone.utc)


def _iso(t: datetime) -> str:
    return t.strftime("%Y-%m-%dT%H:%M:%SZ")


class Collector:
    def __init__(
        self, kube: KubeClient, now: Callable[[], datetime] = _utc_now
    ) -> None:
        self._kube = kube
        self._now = now  # injectable clock (`collector.go:56-61`)

    def collect(self) -> Snapshot:
        """List all nodes + pods, build inventory + summaries
        (`collector.go:64-81`)."""
        nodes = self._kube.list("Node")
        pods = self._kube.list("Pod")
        return Snapshot(
            timestamp=_iso(self._now()),
            tpus=self._build_inventory(nodes, pods),
            pods=self._build_pod_summaries(pods),
        )

    # ------------------------------------------------------------- inventory

    def _build_inventory(self, nodes, pods) -> list[TpuInventory]:
        out: list[TpuInventory] = []
        for node in nodes:
            labels = objects.labels(node)
            model = topology.get_model(labels)
            if model is None:
                whole = topology.pool_model(labels)
                if whole is not None:
                    # Multi-host pool member. A MANAGED member (pool-level
                    # partitioning, tpu/tiling/pool.py) carries status
                    # annotations — its pool shares / host-local slices
                    # report through the primary path like any managed
                    # node. Unmanaged members fall back to capacity;
                    # units are CHIPS (the node's google.com/tpu covers
                    # one host, not the whole pool), so say so.
                    entries = self._inventory_from_annotations(node, whole)
                    if not entries:
                        entries = self._inventory_from_capacity(
                            node,
                            whole,
                            pods,
                            whole_label=(
                                f"{topology.format_shape(whole.host_mesh)}"
                                "-pool chips"
                            ),
                        )
                    out.extend(entries)
                continue
            entries = self._inventory_from_annotations(node, model)
            if not entries:
                entries = self._inventory_from_capacity(node, model, pods)
            out.extend(entries)
        return sorted(out, key=lambda t: t.tpu)

    def _inventory_from_annotations(self, node, model) -> list[TpuInventory]:
        """Primary path: managed nodes' status annotations (`:95-111`)."""
        try:
            status, _ = parse_node_annotations(objects.annotations(node))
        except AnnotationParseError:
            return []
        per_profile: dict[str, dict[str, int]] = {}
        for ann in status:
            bucket = per_profile.setdefault(
                ann.profile, {"used": 0, "free": 0}
            )
            key = "used" if ann.status == DeviceStatus.USED else "free"
            bucket[key] += ann.quantity
        name = objects.name(node)
        return [
            TpuInventory(
                tpu=f"{name}: {model.name} {profile}",
                allocated=counts["used"],
                available=counts["free"],
            )
            for profile, counts in sorted(per_profile.items())
        ]

    def _inventory_from_capacity(
        self, node, model, pods, whole_label: str | None = None
    ) -> list[TpuInventory]:
        """Fallback: capacity minus summed pod requests (`:113-138`).
        `whole_label` overrides the label for whole-TPU (`google.com/tpu`)
        rows, whose counts are chips."""
        capacity: Mapping = (node.get("status") or {}).get("capacity") or {}
        name = objects.name(node)
        out = []
        for resource, raw in sorted(capacity.items()):
            if is_slice_resource(resource):
                profile = extract_profile_name(resource)
            elif resource == constants.RESOURCE_TPU:
                profile = whole_label or topology.format_shape(
                    model.host_mesh
                )
            else:
                continue
            try:
                cap = parse_quantity(raw)
            except ValueError:
                continue
            used = 0
            for pod in pods:
                if (pod.get("spec") or {}).get("nodeName") != name:
                    continue
                # Terminal pods no longer hold devices even though the
                # object persists until GC.
                if (pod.get("status") or {}).get("phase") in (
                    "Succeeded",
                    "Failed",
                ):
                    continue
                if is_slice_resource(resource):
                    used += get_requested_profiles(pod).get(profile, 0)
                else:
                    used += _whole_tpu_request(pod)
            out.append(
                TpuInventory(
                    tpu=f"{name}: {model.name} {profile}",
                    allocated=min(used, cap),
                    available=max(cap - used, 0),
                )
            )
        return out

    # ---------------------------------------------------------- pod summaries

    def _build_pod_summaries(self, pods) -> list[PodSummary]:
        out = []
        for pod in pods:
            profiles = dict(get_requested_profiles(pod))
            whole = _whole_tpu_request(pod)
            if whole:
                profiles[f"{whole}-chips"] = 1
            if not profiles:
                continue
            out.append(
                PodSummary(
                    name=objects.name(pod),
                    namespace=objects.namespace(pod) or "default",
                    status=_pod_status(pod),
                    tpu=_format_profiles(profiles),
                    start_time=_pod_start_time(pod),
                    finish_time=_pod_finish_time(pod),
                )
            )
        return sorted(out, key=lambda p: (p.namespace, p.name))


def _whole_tpu_request(pod: Mapping) -> int:
    total = 0
    for c in (pod.get("spec") or {}).get("containers") or []:
        reqs = (c.get("resources") or {}).get("requests") or {}
        raw = reqs.get(constants.RESOURCE_TPU)
        if raw is None:
            continue
        try:
            total += parse_quantity(raw)
        except ValueError:
            continue
    return total


def _format_profiles(profiles: Mapping[str, int]) -> str:
    """`"2x2 x2, 1x1 x1"` (`formatProfiles`, `collector.go:269-291`)."""
    return ", ".join(
        f"{profile} x{qty}" for profile, qty in sorted(profiles.items())
    )


def _container_statuses_reason(statuses) -> str:
    for status in statuses or []:
        state = status.get("state") or {}
        waiting = state.get("waiting") or {}
        terminated = state.get("terminated") or {}
        if waiting.get("reason"):
            return waiting["reason"]
        if terminated.get("reason"):
            return terminated["reason"]
    return ""


def _pod_status(pod: Mapping) -> str:
    """Container-state reason, else phase, else Unknown (`:199-210`)."""
    status = pod.get("status") or {}
    reason = _container_statuses_reason(status.get("containerStatuses"))
    if not reason:
        reason = _container_statuses_reason(status.get("initContainerStatuses"))
    if reason:
        return reason
    return status.get("phase") or "Unknown"


def _pod_start_time(pod: Mapping) -> str | None:
    return (pod.get("status") or {}).get("startTime")


def _pod_finish_time(pod: Mapping) -> str | None:
    """Latest terminated-at across containers, terminal phases only
    (`:212-233`)."""
    status = pod.get("status") or {}
    if status.get("phase") not in ("Succeeded", "Failed"):
        return None
    latest = None
    for key in ("initContainerStatuses", "containerStatuses"):
        for cs in status.get(key) or []:
            for state_key in ("state", "lastState"):
                term = (cs.get(state_key) or {}).get("terminated") or {}
                t = term.get("finishedAt")
                if t and (latest is None or t > latest):
                    latest = t
    return latest

"""Snapshot schema (`pkg/clusterinfo/types.go:21-43` analogue, TPU-shaped)."""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class TpuInventory:
    """Per-(node, profile) allocation summary (`GPUInventory` analogue)."""

    tpu: str  # "<node>: <accelerator> <profile>", the GPU-name analogue
    allocated: int
    available: int

    def to_dict(self) -> dict:
        return {
            "tpu": self.tpu,
            "allocated": self.allocated,
            "available": self.available,
        }


@dataclass
class PodSummary:
    """One TPU pod (`PodSummary`, `types.go:33-43`)."""

    name: str
    namespace: str
    status: str
    tpu: str  # profiles formatted "2x2 x2, 1x1 x1" (`collector.go:269-291`)
    start_time: str | None = None
    finish_time: str | None = None

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "namespace": self.namespace,
            "status": self.status,
            "tpu": self.tpu,
            "start_time": self.start_time,
            "finish_time": self.finish_time,
        }


@dataclass
class Snapshot:
    """`Snapshot{ts,gpus,pods}` analogue (`types.go:21-27`)."""

    timestamp: str
    tpus: list[TpuInventory] = field(default_factory=list)
    pods: list[PodSummary] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "timestamp": self.timestamp,
            "tpus": [t.to_dict() for t in self.tpus],
            "pods": [p.to_dict() for p in self.pods],
        }

"""One-command product smoke: drive every runtime surface hardware-free.

`make smoke` (or `python hack/smoke.py`) exercises, in order:
  1. the library control-plane flow (tiling search -> spec annotations),
  2. the real controller loops over the sim cluster (node init ->
     pending pod -> retile -> bind -> status ack),
  3. the quota scheduler (bind, over-quota labeling, fair-share
     preemption) against the fake API server,
  4. the JAX entry points (single-chip forward jit + the 8-device
     multi-chip dryrun on a virtual CPU mesh).

Pins JAX to CPU first — verification never touches the real chip
(bench.py owns it).
"""

from __future__ import annotations

import os
import runpy
import subprocess
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def _drive_compute() -> None:
    """Train a tiny LM through the full loop (data pipeline -> sharded
    step -> checkpoint) and generate from it with the KV cache."""
    import numpy as np

    from walkai_nos_tpu.models.data import prefetch_to_device, token_batches
    from walkai_nos_tpu.models.decode import make_generate_fn
    from walkai_nos_tpu.models.lm import (
        LMConfig,
        init_lm_state,
        make_lm_train_step,
    )
    from walkai_nos_tpu.models.trainer import fit
    from walkai_nos_tpu.parallel.mesh import build_mesh
    from walkai_nos_tpu.parallel.sharding import batch_sharding

    cfg = LMConfig(
        vocab_size=64, hidden_dim=32, num_layers=2, num_heads=2,
        max_seq_len=16,
    )
    mesh = build_mesh(jax.devices())
    corpus = np.random.default_rng(0).integers(
        0, cfg.vocab_size, 4096, dtype=np.int32
    )
    batches = prefetch_to_device(
        token_batches(corpus, batch_size=8, seq_len=cfg.max_seq_len),
        sharding=batch_sharding(mesh),
    )
    import tempfile

    state = init_lm_state(cfg, mesh, jax.random.PRNGKey(0))
    with tempfile.TemporaryDirectory() as ckpt_dir:
        result = fit(
            state, make_lm_train_step(cfg, mesh), batches,
            num_steps=8, log_every=0,
            checkpoint_dir=ckpt_dir, checkpoint_every=4,
        )
        assert result.steps_run == 8, result.steps_run
        assert any(os.scandir(ckpt_dir)), "no checkpoint written"
    import jax.numpy as jnp

    out = make_generate_fn(cfg)(
        result.state.params,
        jnp.asarray([[1, 2, 3, 4]], jnp.int32),
        max_new_tokens=4,
    )
    assert out.shape == (1, 4)
    print("compute ok: trained 8 steps, generated", out[0].tolist())

    # Continuous batching: two concurrent requests (one greedy, one
    # sampled) through the slot-pool engine; the greedy one must match
    # the one-shot generate above token for token.
    from walkai_nos_tpu.models.serve import ContinuousBatcher

    engine = ContinuousBatcher(
        cfg, result.state.params, slots=2, cache_len=16, prompt_bucket=8,
        chunk_steps=2,
    )
    greedy_rid = engine.submit([1, 2, 3, 4], max_new_tokens=4)
    sampled_rid = engine.submit(
        [2, 3], max_new_tokens=4, temperature=0.8, seed=7
    )
    results = engine.run()
    assert results[greedy_rid] == out[0].tolist(), results[greedy_rid]
    assert len(results[sampled_rid]) == 4
    print("serve ok: batched greedy == one-shot, sampled co-tenant ran")


def main() -> int:
    for name in ("drive_nos", "drive_quota"):
        print(f"=== {name}")
        runpy.run_path(os.path.join(REPO, "hack", f"{name}.py"))
    print("=== compute runtime (train loop + decode)")
    _drive_compute()
    print("=== jax entry points (subprocess: needs the 8-device flag "
          "before jax backend init)")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS=(
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip(),
    )
    subprocess.run(
        [
            sys.executable,
            "-c",
            (
                "import __graft_entry__ as g; g.dryrun_multichip(8); "
                "fn, args = g.entry(); import jax; jax.jit(fn)(*args); "
                "print('entry + dryrun OK')"
            ),
        ],
        cwd=REPO,
        env=env,
        check=True,
    )
    print("SMOKE OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""One-command product smoke: drive every runtime surface hardware-free.

`make smoke` (or `python hack/smoke.py`) exercises, in order:
  1. the library control-plane flow (tiling search -> spec annotations),
  2. the real controller loops over the sim cluster (node init ->
     pending pod -> retile -> bind -> status ack),
  3. the quota scheduler (bind, over-quota labeling, fair-share
     preemption) against the fake API server,
  4. the JAX entry points (single-chip forward jit + the 8-device
     multi-chip dryrun on a virtual CPU mesh).

Pins JAX to CPU first — verification never touches the real chip
(bench.py owns it).
"""

from __future__ import annotations

import os
import runpy
import subprocess
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main() -> int:
    for name in ("drive_nos", "drive_quota"):
        print(f"=== {name}")
        runpy.run_path(os.path.join(REPO, "hack", f"{name}.py"))
    print("=== jax entry points (subprocess: needs the 8-device flag "
          "before jax backend init)")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS=(
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip(),
    )
    subprocess.run(
        [
            sys.executable,
            "-c",
            (
                "import __graft_entry__ as g; g.dryrun_multichip(8); "
                "fn, args = g.entry(); import jax; jax.jit(fn)(*args); "
                "print('entry + dryrun OK')"
            ),
        ],
        cwd=REPO,
        env=env,
        check=True,
    )
    print("SMOKE OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())

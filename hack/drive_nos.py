"""Smoke: library control-plane flow + real controller loops over SimCluster."""
import os
import sys

# Standalone-runnable: bootstrap the repo root and pin JAX to CPU FIRST
# (AGENTS.md rule: the interpreter may arrive pointed at the real TPU,
# and bench.py owns that chip).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import time

# ---- Surface 1: library flow ------------------------------------------------
from walkai_nos_tpu.tpu.tiling.node import Node
from walkai_nos_tpu.tpu.tiling.known_tilings import clear_known_geometries
from walkai_nos_tpu.tpu.annotations import (
    parse_node_annotations,
    spec_annotations_from_node_partitioning,
)
from walkai_nos_tpu.tpu.tiling.profile import get_requested_profiles

clear_known_geometries()

labels = {
    "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
    "cloud.google.com/gke-tpu-topology": "2x4",
    "nos.walkai.io/tpu-partitioning": "tiling",
}
pod = {
    "metadata": {"name": "j1", "namespace": "default"},
    "spec": {
        "containers": [
            {"resources": {"requests": {"walkai.io/tpu-2x2": "1"}}}
        ]
    },
}
profiles = get_requested_profiles(pod)
assert profiles == {"2x2": 1}, profiles
node = Node.from_node("host-a", labels, {})
ok = node.update_geometry_for(profiles)
assert ok, "update_geometry_for failed"
spec = spec_annotations_from_node_partitioning(node.geometry())
assert spec, "no spec annotations"
assert node.provides_profiles(profiles)
print("surface1 ok:", [(a.mesh_index, a.profile, a.quantity) for a in spec])

# ---- Surface 2: controller loops over SimCluster ---------------------------
from walkai_nos_tpu.sim import SimCluster
from walkai_nos_tpu.kube import objects


def eventually(fn, timeout=30.0, interval=0.2, what=""):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            if fn():
                return
        except Exception as e:  # transient races are expected
            last = e
        time.sleep(interval)
    raise AssertionError(f"eventually({what}) timed out; last={last}")


sim = SimCluster()
sim.add_node("host-a", mesh=(2, 4))
with sim:
    kube = sim.kube

    def node_initialized():
        node = kube.get("Node", "host-a")
        anns = objects.annotations(node)
        status, spec = parse_node_annotations(anns)
        return any(s.profile == "2x4" and s.quantity == 1 for s in spec)

    eventually(node_initialized, what="node init to fewest-slices 2x4")

    sim.create_slice_pod("j1", "2x2")

    def pod_scheduled():
        return objects.pod_is_scheduled(kube.get("Pod", "j1", "default"))

    eventually(pod_scheduled, what="pod j1 scheduled after retile")

    def status_shows_used():
        node = kube.get("Node", "host-a")
        status, spec = parse_node_annotations(objects.annotations(node))
        return any(
            s.profile == "2x2" and s.status.value == "used" and s.quantity >= 1
            for s in status
        )

    eventually(status_shows_used, what="status 2x2 used>=1")

    node = kube.get("Node", "host-a")
    status, spec = parse_node_annotations(objects.annotations(node))
    print("surface2 ok: scheduled with status",
          [(s.profile, s.status.value, s.quantity) for s in status])
print("ALL OK")

"""Shadow/canary plane gate (`make canary-check`, tier-1 via
tests/test_canary.py).

Builds a tiny in-process fleet (two primaries + one canary, 100%
mirror fraction), drives deterministic mixed greedy/seeded-sampled
traffic through the router, and exits 0 only when the same-config
canary reaches the PROMOTE verdict with ZERO digest divergences —
the end-to-end proof that the mirror seam does not change tokens and
the verdict machine converges. With `--inject-divergence` the canary
serves the same config over DIFFERENT weights (the failure class the
digest gate exists for: a config delta cannot explain it) and the
exit code must be NONZERO: 1 when the gate tripped as designed
(REJECT verdict naming the first divergent request/token, flight
bundle on disk), 2 when the divergence was mishandled — the gate
itself is broken. `make canary-check` runs both arms.

CPU-pinned and hardware-free: verdicts ride the purity invariant,
which is exact on every backend, so the cheapest backend gates it.
"""

from __future__ import annotations

import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)


def run_fleet(inject_divergence: bool):
    """Drive one canary-armed fleet to a terminal verdict; returns
    (router.canary_stats(), completed primary records)."""
    import jax
    import numpy as np

    from walkai_nos_tpu.models.lm import DecoderLM, LMConfig
    from walkai_nos_tpu.router.core import PAGE_ROWS, FleetRouter
    from walkai_nos_tpu.sim.trafficbench import default_engine_factory

    cfg = LMConfig(
        vocab_size=64, hidden_dim=32, num_layers=1, num_heads=2,
        max_seq_len=512,
    )
    _, _, factory = default_engine_factory(cfg, None, slots=2)
    replicas = [factory(f"r{i}") for i in range(2)]
    router = FleetRouter(
        replicas, seed=0, canary_mirror=1.0,
        canary_opts={"min_compared": 4, "promote_ticks": 2},
    )
    canary_params = (
        DecoderLM(cfg).init_params(jax.random.PRNGKey(99))
        if inject_divergence else None
    )
    _, _, canary_factory = default_engine_factory(
        cfg, canary_params, slots=2
    )
    canary = canary_factory("canary0")
    for replica in replicas + [canary]:
        replica.warm()
    router.add_replica(canary, role="canary")

    rng = np.random.default_rng(0)
    n = 10
    records: dict[int, dict] = {}
    for i in range(n):
        prompt = rng.integers(
            0, cfg.vocab_size, PAGE_ROWS + 8
        ).astype(np.int32)
        temperature = 1.0 if i % 3 == 0 else 0.0
        router.submit(
            prompt, max_new_tokens=5, temperature=temperature
        )
    for _ in range(80):
        router.step()
        records.update(router.drain_done_records())
        if len(records) >= n and not router.has_work:
            break
    # Verdict ticks keep running after traffic drains (promote needs
    # consecutive clean evaluations; reject is already terminal).
    for _ in range(6):
        router.step()
    return router.canary_stats(), records


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--inject-divergence", action="store_true",
        help="canary serves different WEIGHTS under the same config; "
             "the gate then requires a REJECT verdict",
    )
    args = parser.parse_args(argv)

    stats, records = run_fleet(args.inject_divergence)
    state = stats["state"]
    print(
        f"canary-check: state={state} gate={stats['gate']} "
        f"mirrored={stats['mirrored']} compared={stats['compared']} "
        f"divergences={stats['divergences']} "
        f"primaries_completed={len(records)}"
    )
    if args.inject_divergence:
        # This arm must exit NONZERO: 1 = the gate tripped as
        # designed, 2 = the divergence was mishandled (the gate
        # itself is broken — the worse failure).
        first = stats["first_divergence"]
        if state != "reject" or not first:
            print(
                "canary-check FAILED: injected-weights canary must "
                f"REJECT with a first divergence (state={state}, "
                f"first_divergence={first})"
            )
            return 2
        print(
            f"injected divergence localized: request {first['rid']} "
            f"token {first['token_index']} expected "
            f"{first['expected_token']} got {first['got_token']}; "
            f"flight bundle {first['bundle_path']}"
        )
        if not (
            first["bundle_path"]
            and os.path.isfile(first["bundle_path"])
        ):
            print(
                "canary-check FAILED: no flight bundle on disk for "
                "the divergence"
            )
            return 2
        print(
            "canary-check: injected-divergence arm tripped the gate "
            "as designed"
        )
        return 1
    if state != "promote" or stats["divergences"] != 0:
        print(
            "canary-check FAILED: same-config canary must PROMOTE "
            f"with zero divergences (state={state}, "
            f"divergences={stats['divergences']}, "
            f"reason={stats['verdict_reason']})"
        )
        return 1
    print(
        f"promoted: {stats['verdict_reason']} "
        f"(winning fingerprint {stats['winning_fingerprint']})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Rotating capture-corpus replay gate (`make replay-corpus-check`,
tier-1 via tests/test_replay_corpus.py; ROADMAP item 4(c)).

`hack/replay_check.py` proves ONE fresh capture replays clean;
incidents regress against OLD captures — a config/weights change that
silently moves the serving function on traffic recorded weeks ago.
This gate maintains a size-bounded corpus directory of the last N
captures (each entry one rotated capture set under `NNNN-name/`,
oldest pruned first by count then by total bytes) and replays EVERY
entry through `cmd/replay.py` — the operator CLI — exiting nonzero on
the first divergence.

Run modes:

    python hack/replay_corpus.py
        self-contained gate (the make target / tier-1 pin): build a
        temp corpus from two deterministic runs — a base engine and a
        multi-LoRA-armed engine serving mixed adapter ids (the
        fingerprint's synthetic recipe + per-adapter digests make the
        LoRA replay digest-exact with zero stored adapter weights) —
        then replay the whole corpus.

    python hack/replay_corpus.py CORPUS_DIR [--add CAPTURE] ...
        operator mode: optionally rotate a fresh capture in (pruning
        to --max-captures / --max-bytes), then replay every entry.

CPU-pinned and hardware-free, like every determinism gate here.
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

DEFAULT_MAX_CAPTURES = 8
DEFAULT_MAX_BYTES = 64 * 1024 * 1024


def corpus_entries(corpus_dir: str) -> list[str]:
    """Corpus entries oldest-first: the `NNNN-name` subdirectories
    (zero-padded rotation sequence, so lexical order IS arrival
    order)."""
    if not os.path.isdir(corpus_dir):
        return []
    return sorted(
        os.path.join(corpus_dir, d)
        for d in os.listdir(corpus_dir)
        if os.path.isdir(os.path.join(corpus_dir, d))
        and d[:4].isdigit()
    )


def _entry_bytes(entry: str) -> int:
    total = 0
    for root, _dirs, files in os.walk(entry):
        for fname in files:
            total += os.path.getsize(os.path.join(root, fname))
    return total


def prune_corpus(
    corpus_dir: str,
    *,
    max_captures: int = DEFAULT_MAX_CAPTURES,
    max_bytes: int = DEFAULT_MAX_BYTES,
) -> list[str]:
    """Drop oldest entries while over the count bound, then while
    over the byte bound — but never the newest entry (an oversized
    latest capture must stay replayable rather than empty the
    corpus). Returns the pruned entry paths."""
    pruned: list[str] = []
    entries = corpus_entries(corpus_dir)
    while len(entries) > max(1, max_captures):
        pruned.append(entries.pop(0))
    sizes = {e: _entry_bytes(e) for e in entries}
    while len(entries) > 1 and sum(sizes.values()) > max_bytes:
        victim = entries.pop(0)
        sizes.pop(victim)
        pruned.append(victim)
    for entry in pruned:
        shutil.rmtree(entry, ignore_errors=True)
    return pruned


def add_capture(
    corpus_dir: str,
    capture_path: str,
    *,
    name: str = "capture",
    max_captures: int = DEFAULT_MAX_CAPTURES,
    max_bytes: int = DEFAULT_MAX_BYTES,
) -> str:
    """Rotate one capture (a capture-*.jsonl file or the directory
    holding a rotated set) into the corpus as the newest entry, then
    prune. Returns the new entry path."""
    if not os.path.exists(capture_path):
        raise FileNotFoundError(f"no capture at {capture_path!r}")
    os.makedirs(corpus_dir, exist_ok=True)
    entries = corpus_entries(corpus_dir)
    seq = (
        int(os.path.basename(entries[-1]).split("-", 1)[0]) + 1
        if entries else 0
    )
    entry = os.path.join(corpus_dir, f"{seq:04d}-{name}")
    os.makedirs(entry)
    if os.path.isdir(capture_path):
        for fname in sorted(os.listdir(capture_path)):
            if fname.startswith("capture-") and fname.endswith(".jsonl"):
                shutil.copy2(
                    os.path.join(capture_path, fname), entry
                )
    else:
        shutil.copy2(capture_path, entry)
    prune_corpus(
        corpus_dir, max_captures=max_captures, max_bytes=max_bytes
    )
    return entry


def replay_corpus(
    corpus_dir: str, *, init_seed: int = 0
) -> tuple[int, list[tuple[str, int]]]:
    """Replay every corpus entry through `cmd/replay.py`. Returns
    (worst exit code, [(entry, rc), ...])."""
    from walkai_nos_tpu.cmd.replay import main as replay_main

    results: list[tuple[str, int]] = []
    for entry in corpus_entries(corpus_dir):
        rc = replay_main([entry, "--init-seed", str(init_seed)])
        results.append((entry, rc))
    worst = max((rc for _e, rc in results), default=0)
    return worst, results


def record_lora_traffic(capture_dir: str):
    """One deterministic multi-LoRA traffic run through a
    capture-armed tiny engine: three resident adapters (synthetic
    recipe — the fingerprint carries k/rank/seed/scale so replay
    rebuilds the EXACT adapter weights from the recipe, plus
    per-adapter digests to prove it), requests fanned across adapter
    ids 0/1/2 with mixed greedy/sampled knobs. Returns the completed
    {rid: tokens}."""
    import numpy as np

    import jax

    from walkai_nos_tpu.models.lm import DecoderLM, LMConfig
    from walkai_nos_tpu.models.lora import AdapterSet
    from walkai_nos_tpu.models.serve import ContinuousBatcher

    cfg = LMConfig(
        vocab_size=64, hidden_dim=32, num_layers=1, num_heads=2,
        max_seq_len=320, dtype="float32",
    )
    params = DecoderLM(cfg).init_params(jax.random.PRNGKey(0))
    adapters = AdapterSet.synthetic(cfg, k=3, rank=2, seed=0, scale=0.5)
    engine = ContinuousBatcher(
        cfg, params, slots=2, cache_len=256, prompt_bucket=16,
        chunk_steps=2, paged=True, capture=capture_dir,
        adapters=adapters,
    )
    rng = np.random.default_rng(1)
    for plen, temperature, adapter in (
        (3, 0.0, 1), (140, 0.0, 2), (5, 1.0, 0),
        (9, 1.0, 1), (130, 1.0, 2), (4, 0.0, 0),
    ):
        engine.submit(
            rng.integers(0, cfg.vocab_size, plen).tolist(),
            max_new_tokens=int(rng.integers(3, 9)),
            eos_id=3,
            temperature=temperature,
            adapter=adapter,
        )
    return engine.run()


def build_demo_corpus(
    corpus_dir: str,
    *,
    max_captures: int = DEFAULT_MAX_CAPTURES,
    max_bytes: int = DEFAULT_MAX_BYTES,
) -> list[str]:
    """Record the two deterministic runs (base + multi-LoRA) and
    rotate both into the corpus. Returns the entry paths."""
    import importlib.util

    # hack/ is scripts, not a package — load the sibling by path.
    spec = importlib.util.spec_from_file_location(
        "walkai_replay_check",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "replay_check.py"),
    )
    replay_check = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(replay_check)
    record_traffic = replay_check.record_traffic

    entries = []
    for name, recorder in (
        ("base", record_traffic), ("lora", record_lora_traffic),
    ):
        with tempfile.TemporaryDirectory(
            prefix=f"walkai-corpus-{name}-"
        ) as capture_dir:
            results = recorder(capture_dir)
            print(f"recorded {len(results)} request(s) [{name}]")
            entries.append(add_capture(
                corpus_dir, capture_dir, name=name,
                max_captures=max_captures, max_bytes=max_bytes,
            ))
    return entries


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=(
            "maintain a rotating corpus of serving captures and "
            "replay every entry through cmd/replay.py"
        )
    )
    parser.add_argument(
        "corpus", nargs="?", default=None,
        help="corpus directory (default: self-contained temp corpus "
             "seeded with two deterministic demo runs)",
    )
    parser.add_argument(
        "--add", action="append", default=[], metavar="CAPTURE",
        help="rotate a capture file/dir into the corpus first "
             "(repeatable)",
    )
    parser.add_argument(
        "--max-captures", type=int, default=DEFAULT_MAX_CAPTURES,
    )
    parser.add_argument(
        "--max-bytes", type=int, default=DEFAULT_MAX_BYTES,
    )
    parser.add_argument("--init-seed", type=int, default=0)
    args = parser.parse_args(argv)

    def run(corpus_dir: str) -> int:
        for capture in args.add:
            entry = add_capture(
                corpus_dir, capture,
                max_captures=args.max_captures,
                max_bytes=args.max_bytes,
            )
            print(f"rotated {capture} -> {entry}")
        if args.corpus is None:
            build_demo_corpus(
                corpus_dir, max_captures=args.max_captures,
                max_bytes=args.max_bytes,
            )
        entries = corpus_entries(corpus_dir)
        if not entries:
            print("replay-corpus-check: corpus is empty; nothing to replay")
            return 0
        worst, results = replay_corpus(
            corpus_dir, init_seed=args.init_seed
        )
        for entry, rc in results:
            print(
                f"  {os.path.basename(entry)}: "
                + ("token-identical" if rc == 0 else "DIVERGENT")
            )
        if worst:
            print("replay-corpus-check FAILED: divergent capture(s)")
        else:
            print(f"replay-corpus-check ok ({len(results)} capture(s))")
        return worst

    if args.corpus is not None:
        return run(args.corpus)
    with tempfile.TemporaryDirectory(
        prefix="walkai-replay-corpus-"
    ) as corpus_dir:
        return run(corpus_dir)


if __name__ == "__main__":
    sys.exit(main())

"""Smoke: quota scheduler binding + capacity labels + preemption.

Mirrors the docs' worked example: team-b borrows team-a's unused min,
gets labelled over-quota, and is preempted when team-a reclaims.
"""
import os
import sys

# Standalone-runnable: bootstrap the repo root and pin JAX to CPU FIRST
# (AGENTS.md rule: the interpreter may arrive pointed at the real TPU,
# and bench.py owns that chip).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import time

from walkai_nos_tpu.api import constants
from walkai_nos_tpu.kube.fake import FakeKubeClient
from walkai_nos_tpu.kube import objects
from walkai_nos_tpu.cmd.tpuscheduler import build_manager

CHIPS = constants.RESOURCE_TPU_CHIPS
TPU = constants.RESOURCE_TPU


def eventually(fn, timeout=20.0, interval=0.1, what=""):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            if fn():
                return
        except Exception as e:
            last = e
        time.sleep(interval)
    raise AssertionError(f"eventually({what}) timed out; last={last}")


def mkpod(name, ns, chips, created):
    return {
        "metadata": {"name": name, "namespace": ns,
                     "creationTimestamp": created, "labels": {}},
        "spec": {
            "schedulerName": "walkai-nos-scheduler",
            "containers": [
                {"resources": {"requests": {TPU: str(chips)}}}
            ],
        },
        "status": {"phase": "Pending"},
    }


kube = FakeKubeClient()
kube.create("Node", {
    "metadata": {"name": "host-a"},
    "status": {"allocatable": {TPU: "8"}},
})
kube.create("ElasticQuota", {
    "kind": "ElasticQuota",
    "metadata": {"name": "qa", "namespace": "team-a"},
    "spec": {"min": {CHIPS: "4"}},
}, "team-a")
kube.create("ElasticQuota", {
    "kind": "ElasticQuota",
    "metadata": {"name": "qb", "namespace": "team-b"},
    "spec": {"min": {CHIPS: "4"}},
}, "team-b")

manager = build_manager(kube)
with manager:
    # team-b fills its min, then borrows all of team-a's unused min.
    kube.create("Pod", mkpod("b-0", "team-b", 4, "2026-01-01T00:00:00Z"),
                "team-b")
    kube.create("Pod", mkpod("b-1", "team-b", 4, "2026-01-01T00:01:00Z"),
                "team-b")

    eventually(
        lambda: all(
            kube.get("Pod", f"b-{i}", "team-b")["spec"].get("nodeName")
            for i in range(2)
        ),
        what="team-b pods bind (b-1 borrowing)",
    )
    print("surface3: both team-b pods bound")

    for i in range(2):
        kube.patch("Pod", f"b-{i}", {"status": {"phase": "Running"}}, "team-b")

    eventually(
        lambda: objects.labels(
            kube.get("Pod", "b-1", "team-b")
        ).get("nos.walkai.io/capacity") == "over-quota",
        what="b-1 labelled over-quota",
    )
    print("surface3: borrowing pod labelled over-quota")

    # team-a reclaims its min: the over-quota borrower must be preempted.
    kube.create("Pod", mkpod("a-0", "team-a", 4, "2026-01-01T00:02:00Z"),
                "team-a")

    def reclaimed():
        a0 = kube.get("Pod", "a-0", "team-a")
        try:
            victim = kube.get("Pod", "b-1", "team-b")
            # Eviction may delete the pod or leave it terminal.
            gone = victim["status"].get("phase") in ("Failed", "Succeeded")
        except Exception:
            gone = True
        return bool(a0["spec"].get("nodeName")) and gone

    eventually(reclaimed, what="a-0 bound after b-1 preempted")
    print("surface3 ok: bind + over-quota label + fair-share preemption")
print("ALL OK")

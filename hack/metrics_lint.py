"""Metrics/docs drift gate (`make metrics-lint`, tier-1 via
tests/test_metrics_lint.py).

Holds three surfaces to one truth:

1. `walkai_nos_tpu/obs/catalog.py` — every metric the repo exports,
   declared once (name, type, labels, help);
2. `docs/observability.md` — the human-facing reference: every
   catalog metric must appear as a table row (| `name` | type | ...)
   with the SAME type, and every documented row must exist in the
   catalog — renames and additions fail in BOTH directions;
3. the code itself — a literal-registration scan over walkai_nos_tpu/
   and demos/ (`.counter("..."` / `.gauge("..."` / `.histogram("..."`
   / `counter_add("..."` / `gauge_set("..."`): any literal metric
   name not in the catalog is an undeclared metric and fails. (The
   serving engine registers through the catalog itself, so it cannot
   drift by construction; this catches ad-hoc registrations
   elsewhere.)

Exit 0 = clean; prints each violation otherwise. Stdlib + the
dependency-free catalog module only.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(_ROOT))

from walkai_nos_tpu.obs.catalog import CATALOG  # noqa: E402

DOC = _ROOT / "docs" / "observability.md"

# A documented metric row: | `name` | type | ...
_DOC_ROW = re.compile(
    r"^\|\s*`([A-Za-z_:][A-Za-z0-9_:]*)`\s*\|"
    r"\s*(counter|gauge|histogram)\s*\|"
)

# Literal registrations (the registry API and the health.Metrics
# adapter API). \s* spans newlines: call sites often wrap.
_CODE_PATTERNS = (
    re.compile(r'\.counter\(\s*"([^"]+)"'),
    re.compile(r'\.gauge\(\s*"([^"]+)"'),
    re.compile(r'\.histogram\(\s*"([^"]+)"'),
    re.compile(r'\bcounter_add\(\s*"([^"]+)"'),
    re.compile(r'\bgauge_set\(\s*"([^"]+)"'),
)

_SCAN_DIRS = ("walkai_nos_tpu", "demos")
# Test fixtures register throwaway names on purpose; the registry and
# adapter implementations pass variables, not literals, but skip them
# anyway so an inline example in a docstring can't trip the scan.
_SCAN_SKIP = ("obs/metrics.py", "health.py")


def documented_metrics(doc_text: str) -> dict[str, str]:
    """name -> documented type, from the markdown tables."""
    out: dict[str, str] = {}
    for line in doc_text.splitlines():
        m = _DOC_ROW.match(line.strip())
        if m:
            out[m.group(1)] = m.group(2)
    return out


def registered_literals(root: Path = _ROOT) -> dict[str, list[str]]:
    """literal metric name -> files registering it."""
    out: dict[str, list[str]] = {}
    for sub in _SCAN_DIRS:
        for path in sorted((root / sub).rglob("*.py")):
            rel = str(path.relative_to(root))
            if any(rel.endswith(skip) for skip in _SCAN_SKIP):
                continue
            text = path.read_text()
            for pattern in _CODE_PATTERNS:
                for name in pattern.findall(text):
                    out.setdefault(name, []).append(rel)
    return out


def lint(
    doc_text: str, code_names: dict[str, list[str]] | None = None
) -> list[str]:
    """The testable core: violations as strings (empty = clean)."""
    errors: list[str] = []
    documented = documented_metrics(doc_text)
    catalog = {spec.name: spec for spec in CATALOG}

    for name, spec in sorted(catalog.items()):
        doc_kind = documented.get(name)
        if doc_kind is None:
            errors.append(
                f"catalog metric not documented in "
                f"docs/observability.md: {name} ({spec.kind})"
            )
        elif doc_kind != spec.kind:
            errors.append(
                f"type mismatch for {name}: catalog says {spec.kind}, "
                f"docs say {doc_kind}"
            )
    for name in sorted(set(documented) - set(catalog)):
        errors.append(
            f"documented metric not in obs/catalog.py: {name} "
            f"(remove the row or declare it)"
        )
    for name, files in sorted((code_names or {}).items()):
        if name not in catalog:
            errors.append(
                f"literal metric registration not in obs/catalog.py: "
                f"{name} ({', '.join(sorted(set(files)))})"
            )
    return errors


def main(argv=None) -> int:
    doc_text = DOC.read_text() if DOC.is_file() else ""
    if not doc_text:
        print(f"missing {DOC}")
        return 1
    errors = lint(doc_text, registered_literals())
    for e in errors:
        print(e)
    if errors:
        print(f"{len(errors)} metrics-lint problem(s)")
        return 1
    print(
        f"metrics-lint OK: {len(CATALOG)} catalog metrics documented, "
        f"no undeclared registrations"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

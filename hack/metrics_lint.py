"""Metrics/docs drift gate (`make metrics-lint`, tier-1 via
tests/test_metrics_lint.py).

Holds three surfaces to one truth:

1. `walkai_nos_tpu/obs/catalog.py` — every metric the repo exports,
   declared once (name, type, labels, help);
2. `docs/observability.md` — the human-facing reference: every
   catalog metric must appear as a table row (| `name` | type |
   labels | ...) with the SAME type and the SAME label set, and
   every documented row must exist in the catalog — renames,
   additions, and label drift fail in BOTH directions;
3. the code itself — a literal-registration scan over walkai_nos_tpu/
   and demos/ (`.counter("..."` / `.gauge("..."` / `.histogram("..."`
   / `counter_add("..."` / `gauge_set("..."`): any literal metric
   name not in the catalog is an undeclared metric and fails. (The
   serving engine registers through the catalog itself, so it cannot
   drift by construction; this catches ad-hoc registrations
   elsewhere.)

Plus the FLEET-PLANE rules the serverouter's federated /metrics
relies on, in both directions:

- `router_*` names and `component="router"` imply each other — the
  router catalog half cannot grow a mis-filed spec;
- the `replica` label belongs to router-component specs ONLY: the
  federation layer (`obs/federation.py`) injects it onto every
  re-exported engine series, so an engine metric declaring its own
  would collide;
- every federated prefix in `obs.federation.FEDERATED_PREFIXES` must
  name at least one serving-component catalog family, must not
  collide with the router's own namespace, and must appear on the
  docs' "Federated prefixes:" line — and every prefix documented
  there must exist in code.

Exit 0 = clean; prints each violation otherwise. Stdlib + the
dependency-free catalog/federation modules only.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(_ROOT))

from walkai_nos_tpu.obs.catalog import CATALOG  # noqa: E402
from walkai_nos_tpu.obs.federation import (  # noqa: E402
    FEDERATED_PREFIXES,
)

DOC = _ROOT / "docs" / "observability.md"

# A documented metric row: | `name` | type | labels | ...
_DOC_ROW = re.compile(
    r"^\|\s*`([A-Za-z_:][A-Za-z0-9_:]*)`\s*\|"
    r"\s*(counter|gauge|histogram)\s*\|"
    r"\s*([^|]*)\|"
)
_LABEL_TOKEN = re.compile(r"`([A-Za-z_][A-Za-z0-9_]*)`")
# The docs' federation contract line: "Federated prefixes: `cb_*`".
_FED_LINE = re.compile(
    r"federated prefix(?:es)?:\s*(.+)", re.IGNORECASE
)

# Literal registrations (the registry API and the health.Metrics
# adapter API). \s* spans newlines: call sites often wrap.
_CODE_PATTERNS = (
    re.compile(r'\.counter\(\s*"([^"]+)"'),
    re.compile(r'\.gauge\(\s*"([^"]+)"'),
    re.compile(r'\.histogram\(\s*"([^"]+)"'),
    re.compile(r'\bcounter_add\(\s*"([^"]+)"'),
    re.compile(r'\bgauge_set\(\s*"([^"]+)"'),
)

_SCAN_DIRS = ("walkai_nos_tpu", "demos")
# Test fixtures register throwaway names on purpose; the registry and
# adapter implementations pass variables, not literals, but skip them
# anyway so an inline example in a docstring can't trip the scan.
_SCAN_SKIP = ("obs/metrics.py", "health.py")


def documented_metrics(doc_text: str) -> dict[str, tuple]:
    """name -> (documented type, documented label tuple), from the
    markdown tables (labels are the backticked tokens in the third
    cell; an em-dash cell documents a label-free metric)."""
    out: dict[str, tuple] = {}
    for line in doc_text.splitlines():
        m = _DOC_ROW.match(line.strip())
        if m:
            out[m.group(1)] = (
                m.group(2),
                tuple(_LABEL_TOKEN.findall(m.group(3))),
            )
    return out


def documented_federated_prefixes(doc_text: str) -> set[str]:
    """Prefixes the docs declare as federated (the "Federated
    prefixes: `cb_*`" contract line in the Fleet plane section)."""
    out: set[str] = set()
    for line in doc_text.splitlines():
        m = _FED_LINE.search(line)
        if m:
            out.update(
                re.findall(r"`([a-z0-9_]+)\*`", m.group(1))
            )
    return out


def registered_literals(root: Path = _ROOT) -> dict[str, list[str]]:
    """literal metric name -> files registering it."""
    out: dict[str, list[str]] = {}
    for sub in _SCAN_DIRS:
        for path in sorted((root / sub).rglob("*.py")):
            rel = str(path.relative_to(root))
            if any(rel.endswith(skip) for skip in _SCAN_SKIP):
                continue
            text = path.read_text()
            for pattern in _CODE_PATTERNS:
                for name in pattern.findall(text):
                    out.setdefault(name, []).append(rel)
    return out


def lint(
    doc_text: str, code_names: dict[str, list[str]] | None = None
) -> list[str]:
    """The testable core: violations as strings (empty = clean)."""
    errors: list[str] = []
    documented = documented_metrics(doc_text)
    catalog = {spec.name: spec for spec in CATALOG}

    for name, spec in sorted(catalog.items()):
        row = documented.get(name)
        if row is None:
            errors.append(
                f"catalog metric not documented in "
                f"docs/observability.md: {name} ({spec.kind})"
            )
            continue
        doc_kind, doc_labels = row
        if doc_kind != spec.kind:
            errors.append(
                f"type mismatch for {name}: catalog says {spec.kind}, "
                f"docs say {doc_kind}"
            )
        if set(doc_labels) != set(spec.labels):
            errors.append(
                f"label mismatch for {name}: catalog says "
                f"{sorted(spec.labels) or '—'}, docs say "
                f"{sorted(doc_labels) or '—'}"
            )
    for name in sorted(set(documented) - set(catalog)):
        errors.append(
            f"documented metric not in obs/catalog.py: {name} "
            f"(remove the row or declare it)"
        )
    for name, files in sorted((code_names or {}).items()):
        if name not in catalog:
            errors.append(
                f"literal metric registration not in obs/catalog.py: "
                f"{name} ({', '.join(sorted(set(files)))})"
            )
    # Fleet-plane rules (both directions): the router catalog half
    # and the federation's `replica`-label contract.
    for name, spec in sorted(catalog.items()):
        if name.startswith("router_") != (spec.component == "router"):
            errors.append(
                f"router namespace rule: {name} has "
                f"component={spec.component!r} — router_* names and "
                f"component='router' must imply each other"
            )
        if "replica" in spec.labels and spec.component != "router":
            errors.append(
                f"replica-label rule: {name} "
                f"(component={spec.component!r}) declares a "
                f"'replica' label — federation injects that label "
                f"onto re-exported series, so only router-component "
                f"metrics may carry it"
            )
    doc_prefixes = documented_federated_prefixes(doc_text)
    for prefix in sorted(FEDERATED_PREFIXES):
        if prefix.startswith("router_") or "router_".startswith(
            prefix
        ):
            errors.append(
                f"federated prefix {prefix}* collides with the "
                f"router's own namespace"
            )
        if not any(
            spec.name.startswith(prefix)
            and spec.component == "serving"
            for spec in CATALOG
        ):
            errors.append(
                f"federated prefix {prefix}* matches no "
                f"serving-component catalog metric"
            )
        if prefix not in doc_prefixes:
            errors.append(
                f"federated prefix {prefix}* not documented on the "
                f"docs' 'Federated prefixes:' line"
            )
    for prefix in sorted(doc_prefixes - set(FEDERATED_PREFIXES)):
        errors.append(
            f"docs declare federated prefix {prefix}* but "
            f"obs/federation.py FEDERATED_PREFIXES does not"
        )
    return errors


def main(argv=None) -> int:
    doc_text = DOC.read_text() if DOC.is_file() else ""
    if not doc_text:
        print(f"missing {DOC}")
        return 1
    errors = lint(doc_text, registered_literals())
    for e in errors:
        print(e)
    if errors:
        print(f"{len(errors)} metrics-lint problem(s)")
        return 1
    print(
        f"metrics-lint OK: {len(CATALOG)} catalog metrics documented, "
        f"no undeclared registrations"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Capture/replay determinism gate (`make replay-check`, tier-1 via
tests/test_capture_replay.py).

Records a small deterministic traffic run through a capture-armed
`ContinuousBatcher` (mixed greedy and seeded-sampled ragged requests,
a block-boundary-crossing prompt included), then replays the capture
through `cmd/replay.py` — the same CLI an operator replays an
incident with — and exits nonzero on ANY divergence. A second replay
runs under a `loop_steps` override, so the gate also holds the
device-resident fold to the "replay changes WHEN the host learns
about tokens, never WHICH" contract.

CPU-pinned and hardware-free: the determinism invariant is exact on
every backend, so the cheapest backend gates it.
"""

from __future__ import annotations

import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)


def record_traffic(capture_dir: str):
    """One deterministic mixed traffic run through a capture-armed
    tiny engine; returns the engine's completed {rid: tokens} so a
    caller (the tier-1 test) can cross-check the capture contents."""
    import numpy as np

    import jax

    from walkai_nos_tpu.models.lm import DecoderLM, LMConfig
    from walkai_nos_tpu.models.serve import ContinuousBatcher

    cfg = LMConfig(
        vocab_size=64, hidden_dim=32, num_layers=1, num_heads=2,
        max_seq_len=320, dtype="float32",
    )
    params = DecoderLM(cfg).init_params(jax.random.PRNGKey(0))
    engine = ContinuousBatcher(
        cfg, params, slots=2, cache_len=256, prompt_bucket=16,
        chunk_steps=2, capture=capture_dir,
    )
    rng = np.random.default_rng(0)
    # Mixed greedy/sampled, ragged lengths, one prompt crossing the
    # 128-row block boundary, budgets that EOS-terminate sometimes.
    for plen, temperature in (
        (3, 0.0), (140, 0.0), (5, 1.0), (9, 1.0), (130, 1.0), (4, 0.0),
    ):
        engine.submit(
            rng.integers(0, cfg.vocab_size, plen).tolist(),
            max_new_tokens=int(rng.integers(3, 9)),
            eos_id=3,
            temperature=temperature,
        )
    return engine.run()


def main(argv=None) -> int:
    from walkai_nos_tpu.cmd.replay import main as replay_main

    with tempfile.TemporaryDirectory(
        prefix="walkai-replay-check-"
    ) as capture_dir:
        results = record_traffic(capture_dir)
        print(
            f"recorded {len(results)} request(s) to {capture_dir}; "
            f"replaying..."
        )
        rc = replay_main([capture_dir, "--init-seed", "0"])
        if rc != 0:
            print("replay-check FAILED: same-config replay diverged")
            return rc
        rc = replay_main(
            [capture_dir, "--init-seed", "0",
             "--override", "loop_steps=4"]
        )
        if rc != 0:
            print(
                "replay-check FAILED: loop_steps=4 replay diverged "
                "(the device-resident fold changed WHICH tokens, not "
                "just when the host learns them)"
            )
            return rc
    print("replay-check ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())

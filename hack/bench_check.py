"""Regression gate for the headline bench keys.

Compares `bench_last.json` (the sidecar the last `python bench.py` run
wrote) against the baselines recorded in `BASELINE.json`'s `published`
map and exits nonzero when any key regresses past its tolerance band —
the `make bench-check` target, and a tier-1 test
(tests/test_bench_check.py) pins the comparison logic plus the repo's
own current files.

`published` entries are either a bare number (higher-is-better, the
default 25% band) or a spec:

    "cb_serving_capacity_tokens_per_s":
        {"value": 3583.7, "direction": "higher", "tolerance": 0.25}

- direction "higher": fail when measured < value * (1 - tolerance)
- direction "lower"  (latencies): fail when measured > value * (1 + tolerance)
- value null: baseline not yet recorded (the key postdates the last
  recorded round) — skipped with a note, never a failure, so new
  metrics can be declared before a chip run exists to anchor them.
- "absent_ok": true — a BUDGET key (e.g. obs_overhead_pct's absolute
  < 2% ceiling with tolerance 0, or the prefix cache's
  cb_prefix_hit_rate / cb_prefill_tokens_saved_frac acceptance
  floors): when the key is missing from the bench output (the
  recorded artifact predates the key), skip with a note instead of
  failing; once a bench run emits it, the band is enforced like any
  other. This is how an absolute gate ships before the next chip run
  records a measurement.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

DEFAULT_TOLERANCE = 0.25
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def check(
    bench: dict, baseline: dict, *, tolerance: float = DEFAULT_TOLERANCE
) -> tuple[list[str], list[str]]:
    """(failures, notes) from comparing a bench result against the
    baseline's `published` map. Pure — the testable core."""
    failures: list[str] = []
    notes: list[str] = []
    published = baseline.get("published") or {}
    for key, spec in sorted(published.items()):
        absent_ok = False
        if isinstance(spec, dict):
            base = spec.get("value")
            direction = spec.get("direction", "higher")
            tol = spec.get("tolerance", tolerance)
            absent_ok = bool(spec.get("absent_ok", False))
        else:
            base, direction, tol = spec, "higher", tolerance
        if base is None:
            notes.append(f"{key}: no recorded baseline yet — skipped")
            continue
        got = bench.get(key)
        if not isinstance(got, (int, float)):
            if absent_ok:
                notes.append(
                    f"{key}: absent from bench output — skipped "
                    f"(absent_ok budget key; enforced once emitted)"
                )
                continue
            failures.append(
                f"{key}: missing from bench output "
                f"(baseline {base}, {direction} is better)"
            )
            continue
        if direction == "higher" and got < base * (1 - tol):
            # A zero baseline is a hard floor (e.g. divergence
            # counts): no relative % exists for it.
            rel = (
                f"{100 * (1 - got / base):.1f}% below" if base
                else "below"
            )
            failures.append(
                f"{key}: {got} is {rel} "
                f"baseline {base} (tolerance {tol:.0%})"
            )
        elif direction == "lower" and got > base * (1 + tol):
            rel = (
                f"{100 * (got / base - 1):.1f}% above" if base
                else "above"
            )
            failures.append(
                f"{key}: {got} is {rel} "
                f"baseline {base} (tolerance {tol:.0%}, lower is better)"
            )
        else:
            notes.append(f"{key}: {got} vs baseline {base} — ok")
    return failures, notes


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--bench", default=os.path.join(_ROOT, "bench_last.json"),
        help="bench result JSON (default: repo bench_last.json)",
    )
    ap.add_argument(
        "--baseline", default=os.path.join(_ROOT, "BASELINE.json"),
        help="baseline JSON with a `published` map",
    )
    args = ap.parse_args(argv)
    with open(args.bench) as f:
        bench = json.load(f)
    with open(args.baseline) as f:
        baseline = json.load(f)
    failures, notes = check(bench, baseline)
    for line in notes:
        print(f"  {line}")
    if failures:
        print("bench-check FAILED:")
        for line in failures:
            print(f"  {line}")
        return 1
    print("bench-check ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())

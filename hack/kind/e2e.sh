#!/usr/bin/env bash
# Scripted kind-cluster e2e: the §7.3 scenario against a REAL API server,
# kubelet, and scheduler — the analogue of the reference's envtest suites
# (`internal/controllers/migagent/suite_int_test.go:33-163`) plus its kind
# flow (`Makefile:115-117`, `hack/kind/cluster.yaml`).
#
# Flow: build + load the image, helm-install with WALKAI_TPUDEV_FAKE
# agents (fake chips, real device-plugin gRPC registration with the
# node's kubelet), label a worker as a 2x4 TPU host, then:
#   node init -> agent materializes + reports -> pending 2x2 pod ->
#   partitioner re-tiles -> kubelet re-advertises -> pod schedules.
#
# Usage: hack/kind/e2e.sh [cluster-name]   (cluster must already exist:
# `make kind-cluster`, or let `make e2e-kind` create it)
set -euo pipefail

CLUSTER=${1:-walkai-nos}
IMG=${IMG:-ghcr.io/walkai/nos-tpu:e2e}
NS=walkai-nos
WORKER="${CLUSTER}-worker"

say() { echo ">>> $*"; }

# Poll a node's JSON for a marker string (annotation prefix) for up to
# 120s; FAIL with the given message if it never appears.
wait_for_node_annotation() {
  local node=$1 marker=$2 what=$3
  for i in $(seq 1 60); do
    kubectl get node "${node}" -o json | grep -q "${marker}" && return 0
    sleep 2
  done
  echo "FAIL: ${what}"
  exit 1
}

say "building image ${IMG}"
docker build -f build/Dockerfile -t "${IMG}" .
kind load docker-image --name "${CLUSTER}" "${IMG}"

say "installing chart with fake tpudev (2x4 mesh)"
# Every enabled component must run the locally built image; the
# kube-rbac-proxy sidecar is disabled so the flow has no external image
# dependencies beyond busybox.
helm upgrade --install walkai-nos helm-charts/walkai-nos-tpu \
  --namespace "${NS}" --create-namespace \
  --set partitioner.image.repository="${IMG%:*}" \
  --set partitioner.image.tag="${IMG##*:}" \
  --set agent.image.repository="${IMG%:*}" \
  --set agent.image.tag="${IMG##*:}" \
  --set scheduler.image.repository="${IMG%:*}" \
  --set scheduler.image.tag="${IMG##*:}" \
  --set clusterInfoExporter.enabled=false \
  --set kubeRbacProxy.enabled=false \
  --set agent.extraEnv[0].name=WALKAI_TPUDEV_FAKE \
  --set agent.extraEnv[0].value=2x4 \
  --set sharingAgent.enabled=true \
  --set sharingAgent.image.repository="${IMG%:*}" \
  --set sharingAgent.image.tag="${IMG##*:}" \
  --set sharingAgent.extraEnv[0].name=WALKAI_TPUDEV_FAKE \
  --set sharingAgent.extraEnv[0].value=2x4 \
  --wait --timeout 180s

say "labeling ${WORKER} as a v5e 2x4 TPU host (tiling)"
kubectl label node "${WORKER}" --overwrite \
  cloud.google.com/gke-tpu-accelerator=tpu-v5-lite-podslice \
  cloud.google.com/gke-tpu-topology=2x4 \
  nos.walkai.io/tpu-partitioning=tiling

# Label worker2 for sharing BEFORE any pod is created: nodes are
# first-fit candidates in API order, so a still-tiling worker2 could
# otherwise capture the tiling pod and then be relabeled under it.
WORKER2="${CLUSTER}-worker2"
if kubectl get node "${WORKER2}" >/dev/null 2>&1; then
  say "labeling ${WORKER2} as a chip-count-sharing host"
  kubectl label node "${WORKER2}" --overwrite \
    cloud.google.com/gke-tpu-accelerator=tpu-v5-lite-podslice \
    cloud.google.com/gke-tpu-topology=2x4 \
    nos.walkai.io/tpu-partitioning=sharing
fi

say "waiting for node init (spec annotations)"
wait_for_node_annotation "${WORKER}" 'nos.walkai.io/spec-tpu' \
  "node never initialized"

say "waiting for agent status report"
wait_for_node_annotation "${WORKER}" 'nos.walkai.io/status-tpu' \
  "agent never reported"

say "creating a pending 2x2 slice pod"
kubectl apply -f - <<EOF
apiVersion: v1
kind: Pod
metadata:
  name: e2e-slice-pod
  namespace: default
spec:
  restartPolicy: Never
  containers:
    - name: main
      image: busybox:1.36
      command: ["sleep", "300"]
      resources:
        requests: {"walkai.io/tpu-2x2": "1"}
        limits: {"walkai.io/tpu-2x2": "1"}
EOF

say "waiting for the pod to schedule (retile -> advertise -> bind)"
if ! kubectl wait pod/e2e-slice-pod --for=condition=PodScheduled \
    --timeout=180s; then
  echo "FAIL: pod never scheduled"
  kubectl describe pod e2e-slice-pod | tail -20
  kubectl -n "${NS}" logs -l app.kubernetes.io/component=partitioner \
    --tail=50 || true
  exit 1
fi

say "tiling scenario PASS"

# ---- dynamic sharing scenario (second worker, labeled above) ----------
if kubectl get node "${WORKER2}" >/dev/null 2>&1; then
  say "creating a pending 2c share pod"
  kubectl apply -f - <<EOF
apiVersion: v1
kind: Pod
metadata:
  name: e2e-share-pod
  namespace: default
spec:
  restartPolicy: Never
  containers:
    - name: main
      image: busybox:1.36
      command: ["sleep", "300"]
      resources:
        requests: {"walkai.io/tpu-shared-2c": "1"}
        limits: {"walkai.io/tpu-shared-2c": "1"}
EOF

  say "waiting for the share pod to schedule (plan -> advertise -> bind)"
  if ! kubectl wait pod/e2e-share-pod --for=condition=PodScheduled \
      --timeout=180s; then
    echo "FAIL: share pod never scheduled"
    kubectl describe pod e2e-share-pod | tail -20
    kubectl -n "${NS}" logs -l app=tpusharingagent --tail=50 || true
    kubectl -n "${NS}" logs -l app.kubernetes.io/component=partitioner \
      --tail=50 || true
    exit 1
  fi
  say "sharing scenario PASS"
else
  say "no ${WORKER2} in this cluster; skipping the sharing scenario"
fi

# ---- multi-host pool scenario (workers 3+4, labeled by cluster.yaml) --
WORKER3="${CLUSTER}-worker3"
WORKER4="${CLUSTER}-worker4"
if kubectl get node "${WORKER3}" >/dev/null 2>&1 \
    && kubectl get node "${WORKER4}" >/dev/null 2>&1; then
  say "pool scenario: waiting for pool members to init (share spec 2x8)"
  for node in "${WORKER3}" "${WORKER4}"; do
    wait_for_node_annotation "${node}" 'nos.walkai.io/spec-tpu-0-2x8' \
      "pool member ${node} never initialized"
  done

  say "creating a 2-pod gang, each consuming one 2x8 share"
  for idx in 0 1; do
    kubectl apply -f - <<EOF
apiVersion: v1
kind: Pod
metadata:
  name: e2e-gang-${idx}
  namespace: default
spec:
  restartPolicy: Never
  containers:
    - name: main
      image: busybox:1.36
      command: ["sleep", "300"]
      resources:
        requests: {"walkai.io/tpu-2x8": "1"}
        limits: {"walkai.io/tpu-2x8": "1"}
EOF
  done

  say "waiting for the gang to bind one pod per member host"
  for idx in 0 1; do
    if ! kubectl wait pod/e2e-gang-${idx} \
        --for=condition=PodScheduled --timeout=180s; then
      echo "FAIL: gang pod ${idx} never scheduled"
      kubectl describe pod e2e-gang-${idx} | tail -20
      # Pool-share actuation failures surface in the AGENT logs
      # (actuator pool-share path), not the partitioner's.
      kubectl -n "${NS}" logs -l app=tpuagent --tail=50 || true
      kubectl -n "${NS}" logs \
        -l app.kubernetes.io/component=partitioner --tail=50 || true
      exit 1
    fi
  done
  HOSTS=$(kubectl get pod e2e-gang-0 e2e-gang-1 \
    -o jsonpath='{.items[*].spec.nodeName}' | tr ' ' '\n' | sort -u \
    | wc -l)
  [ "${HOSTS}" -eq 2 ] \
    || { echo "FAIL: gang pods share a host"; exit 1; }
  say "pool scenario PASS"
else
  say "no ${WORKER3}/${WORKER4} in this cluster; skipping the pool scenario"
fi

# ---- elastic-quota scenario (tpuscheduler binds, denies over-max) -----
# Runs in its OWN namespace: quota accounting counts every bound
# non-terminal pod in the namespace (quota/state.py), so the earlier
# scenarios' sleeping pods in `default` must not be in scope.
QNS=e2e-quota
say "quota scenario: ElasticQuota min=max=4 chips in namespace ${QNS}"
# The chart ships the CRDs (helm-charts/walkai-nos-tpu/crds/); this is
# belt-and-braces for clusters where helm skipped existing CRDs.
kubectl apply -f deploy/crds/elasticquota.yaml
kubectl wait --for condition=established --timeout=60s \
  crd/elasticquotas.nos.walkai.io crd/compositeelasticquotas.nos.walkai.io
kubectl create namespace "${QNS}" --dry-run=client -o yaml | kubectl apply -f -
kubectl apply -f - <<EOF
apiVersion: nos.walkai.io/v1alpha1
kind: ElasticQuota
metadata:
  name: e2e-quota
  namespace: ${QNS}
spec:
  min: {nos.walkai.io/tpu-chips: "4"}
  max: {nos.walkai.io/tpu-chips: "4"}
EOF

say "creating a quota-scheduled 2x2 pod (4 chips, within min)"
kubectl apply -f - <<EOF
apiVersion: v1
kind: Pod
metadata:
  name: e2e-quota-pod
  namespace: ${QNS}
spec:
  schedulerName: walkai-nos-scheduler
  restartPolicy: Never
  containers:
    - name: main
      image: busybox:1.36
      command: ["sleep", "300"]
      resources:
        requests: {"walkai.io/tpu-2x2": "1"}
        limits: {"walkai.io/tpu-2x2": "1"}
EOF

say "waiting for the quota pod to bind (scheduler -> retile -> bind)"
if ! kubectl -n "${QNS}" wait pod/e2e-quota-pod \
    --for=condition=PodScheduled --timeout=180s; then
  echo "FAIL: quota pod never scheduled"
  kubectl -n "${QNS}" describe pod e2e-quota-pod | tail -20
  kubectl -n "${NS}" logs -l app=tpuscheduler --tail=50 || true
  exit 1
fi

say "creating a second 2x2 pod that exceeds max (8 > 4 chips)"
kubectl apply -f - <<EOF
apiVersion: v1
kind: Pod
metadata:
  name: e2e-overquota-pod
  namespace: ${QNS}
spec:
  schedulerName: walkai-nos-scheduler
  restartPolicy: Never
  containers:
    - name: main
      image: busybox:1.36
      command: ["sleep", "300"]
      resources:
        requests: {"walkai.io/tpu-2x2": "1"}
        limits: {"walkai.io/tpu-2x2": "1"}
EOF

say "asserting the over-max pod is QUOTA-denied (not a capacity miss)"
sleep 20
if [ -n "$(kubectl -n "${QNS}" get pod e2e-overquota-pod \
    -o jsonpath='{.spec.nodeName}')" ]; then
  echo "FAIL: over-quota pod was bound past the quota max"
  kubectl -n "${NS}" logs -l app=tpuscheduler --tail=50 || true
  exit 1
fi
# Distinguish the denial path: quota denials deliberately do NOT write
# the Unschedulable condition (retiling can't create quota headroom,
# cmd/tpuscheduler.py), so its presence means the capacity path ran and
# this assertion would be vacuous.
if kubectl -n "${QNS}" get pod e2e-overquota-pod \
    -o jsonpath='{.status.conditions[?(@.reason=="Unschedulable")]}' \
    | grep -q Unschedulable; then
  echo "FAIL: over-quota pod hit the capacity path, not quota denial"
  kubectl -n "${NS}" logs -l app=tpuscheduler --tail=50 || true
  exit 1
fi
kubectl -n "${NS}" logs -l app=tpuscheduler --tail=200 \
  | grep "quota-denied" | grep -q e2e-overquota-pod \
  || { echo "FAIL: scheduler never logged a quota denial"; exit 1; }
say "quota scenario PASS"

say "PASS: e2e scenario complete"
kubectl get node "${WORKER}" -o jsonpath='{.metadata.annotations}' \
  | tr ',' '\n' | grep nos.walkai.io | sed 's/^/    /'

"""Dependency-free docs-site structural check (mkdocs --strict analogue).

The reference builds its docs with mkdocs (`docs/mkdocs.yaml`); this repo
mirrors that config, and CI runs the real `mkdocs build --strict` when
mkdocs is installed. This checker is the always-available half — stdlib
only, run by CI and `tests/test_manifests.py` — so links rot loudly even
where mkdocs cannot be installed:

1. every nav entry in mkdocs.yaml points at an existing file;
2. every markdown file under docs/ is reachable from the nav or the
   docs index (no orphan pages);
3. every relative markdown link in every docs page resolves to a file.

Exit code 0 = clean; prints each violation otherwise.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

import yaml

DOCS = Path(__file__).resolve().parent.parent / "docs"

# In-page http(s)/mail/anchor links are out of scope; relative .md links
# (optionally with an #anchor) must resolve.
_LINK_RE = re.compile(r"\[[^\]]*\]\(([^)\s]+)\)")


def nav_files(nav) -> list[str]:
    out: list[str] = []
    for entry in nav:
        if isinstance(entry, str):
            out.append(entry)
        elif isinstance(entry, dict):
            for value in entry.values():
                if isinstance(value, str):
                    out.append(value)
                else:
                    out.extend(nav_files(value))
    return out


def main() -> int:
    errors: list[str] = []
    config = yaml.safe_load(
        (DOCS.parent / "mkdocs.yaml").read_text()
    )
    nav = nav_files(config.get("nav") or [])

    # 1. Nav entries exist.
    for rel in nav:
        if not (DOCS / rel).is_file():
            errors.append(f"nav entry missing: docs/{rel}")

    # 2. No orphan pages: every docs/*.md is in nav or linked from the
    # docs index (README.md, the repo-browsing entry point).
    reachable = {str(Path(rel)) for rel in nav}
    index = DOCS / "README.md"
    if index.is_file():
        reachable.add("README.md")
        for link in _LINK_RE.findall(index.read_text()):
            target = link.split("#", 1)[0]
            if target.endswith(".md"):
                reachable.add(str(Path(target)))
    for page in sorted(DOCS.rglob("*.md")):
        rel = str(page.relative_to(DOCS))
        if rel not in reachable:
            errors.append(
                f"orphan page (not in mkdocs nav or docs/README.md): "
                f"docs/{rel}"
            )

    # 3. Relative markdown links resolve.
    for page in sorted(DOCS.rglob("*.md")):
        for link in _LINK_RE.findall(page.read_text()):
            target = link.split("#", 1)[0]
            if (
                not target
                or "://" in target
                or target.startswith("mailto:")
            ):
                continue
            if not target.endswith((".md", ".yaml", ".yml", ".py", ".sh")):
                continue
            resolved = (page.parent / target).resolve()
            if not resolved.exists():
                errors.append(
                    f"broken link in docs/{page.relative_to(DOCS)}: "
                    f"{link}"
                )

    for e in errors:
        print(e)
    if errors:
        print(f"{len(errors)} docs problem(s)")
        return 1
    print(f"docs OK: {len(nav)} nav pages, no orphans, links resolve")
    return 0


if __name__ == "__main__":
    sys.exit(main())

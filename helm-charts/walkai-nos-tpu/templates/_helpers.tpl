{{- define "walkai-nos.image" -}}
{{ .Values.image.repository }}:{{ .Values.image.tag }}
{{- end -}}

{{- define "walkai-nos.labels" -}}
app.kubernetes.io/part-of: walkai-nos-tpu
app.kubernetes.io/managed-by: {{ .Release.Service }}
helm.sh/chart: {{ .Chart.Name }}-{{ .Chart.Version }}
{{- end -}}

{{/*
Create chart name and version as used by the chart label
(reference: helm-charts/nos/templates/_helpers.tpl).
*/}}
{{- define "walkai-nos.chart" -}}
{{- printf "%s-%s" .Chart.Name .Chart.Version | replace "+" "_" | trunc 63 | trimSuffix "-" }}
{{- end }}

{{/*
Full name including the release name.
*/}}
{{- define "walkai-nos.fullname" -}}
{{- if .Values.fullnameOverride -}}
{{- .Values.fullnameOverride | trunc 63 | trimSuffix "-" -}}
{{- else -}}
{{- $name := .Chart.Name -}}
{{- if contains $name .Release.Name -}}
{{- .Release.Name | trunc 63 | trimSuffix "-" -}}
{{- else -}}
{{- (printf "%s-%s" .Release.Name $name) | trunc 63 | trimSuffix "-" -}}
{{- end -}}
{{- end -}}
{{- end -}}

{{/*
Common labels.
*/}}
{{- define "walkai-nos.labels" -}}
helm.sh/chart: {{ include "walkai-nos.chart" . }}
app.kubernetes.io/name: {{ .Chart.Name }}
app.kubernetes.io/instance: {{ .Release.Name }}
{{- if .Chart.AppVersion }}
app.kubernetes.io/version: {{ .Chart.AppVersion | quote }}
{{- end }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
{{- end }}

{{/*
Per-component image refs: tag defaults to the chart appVersion
(reference: values.yaml image.tag docs).
*/}}
{{- define "walkai-nos.partitioner.image" -}}
{{ .Values.partitioner.image.repository }}:{{ .Values.partitioner.image.tag | default .Chart.AppVersion }}
{{- end -}}

{{- define "walkai-nos.agent.image" -}}
{{ .Values.agent.image.repository }}:{{ .Values.agent.image.tag | default .Chart.AppVersion }}
{{- end -}}

{{- define "walkai-nos.sharingAgent.image" -}}
{{ .Values.sharingAgent.image.repository }}:{{ .Values.sharingAgent.image.tag | default .Chart.AppVersion }}
{{- end -}}

{{- define "walkai-nos.scheduler.image" -}}
{{ .Values.scheduler.image.repository }}:{{ .Values.scheduler.image.tag | default .Chart.AppVersion }}
{{- end -}}

{{- define "walkai-nos.clusterInfoExporter.image" -}}
{{ .Values.clusterInfoExporter.image.repository }}:{{ .Values.clusterInfoExporter.image.tag | default .Chart.AppVersion }}
{{- end -}}

{{- define "walkai-nos.kubeRbacProxy.image" -}}
{{ .Values.kubeRbacProxy.image.repository }}:{{ .Values.kubeRbacProxy.image.tag }}
{{- end -}}

{{/*
kube-rbac-proxy sidecar container protecting 127.0.0.1:8080 /metrics
(reference: helm-charts/nos/values.yaml:41-55 + the auth-proxy
clusterrole in templates/gpu-partitioner/).
*/}}
{{- define "walkai-nos.kubeRbacProxy.container" -}}
- name: kube-rbac-proxy
  image: {{ include "walkai-nos.kubeRbacProxy.image" . }}
  imagePullPolicy: {{ .Values.kubeRbacProxy.image.pullPolicy }}
  args:
    - --secure-listen-address=0.0.0.0:8443
    - --upstream=http://127.0.0.1:8080/
    - --logtostderr=true
    - --v={{ .Values.kubeRbacProxy.logLevel }}
  ports:
    - containerPort: 8443
      name: https-metrics
  resources:
    {{- toYaml .Values.kubeRbacProxy.resources | nindent 4 }}
{{- end -}}

{{/*
ConfigMap names used by the UUID-persistence pattern
(reference: _helpers.tpl nos.installationInfoConfigMap.name).
*/}}
{{- define "walkai-nos.metricsConfigMap.name" -}}
{{- printf "%s-metrics" (include "walkai-nos.fullname" .) -}}
{{- end -}}

{{- define "walkai-nos.installationInfoConfigMap.name" -}}
{{- printf "%s-installation-info" (include "walkai-nos.fullname" .) -}}
{{- end -}}
